"""Python API client (the pxapi analog).

Reference parity: ``/root/reference/src/api/go/pxapi/client.go:41-54``
(``Client.ExecuteScript`` streaming results into per-table record
handlers) and the Python client under ``src/api/python``. The transport
is the framed-TCP netbus to a served broker; results arrive as
HostBatches and are surfaced row-wise through handlers or as pydicts.

    import pixie_tpu.api as pxapi

    client = pxapi.Client("127.0.0.1", 6100)
    for table, rows in client.execute_script(pxl).items():
        ...

    # or streaming-handler style:
    class Printer(pxapi.TableRecordHandler):
        def handle_record(self, record): print(record)
    client.execute_script(pxl, handler_factory=lambda t: Printer())
"""

from __future__ import annotations

from typing import Callable, Optional

#: Control-plane requests that are safe to retry on a BusTimeout: pure
#: reads with no side effects. broker.execute is deliberately NOT here
#: — re-running a script blind could double-execute mutations; it
#: re-resolves the leader and surfaces a structured error instead
#: (docs/RESILIENCE.md "Broker HA").
_IDEMPOTENT_TOPICS = frozenset({
    "broker.scripts", "broker.schemas", "broker.agents",
    "broker.debug_queries", "broker.profile", "broker.leader",
})


class ScriptExecutionError(RuntimeError):
    pass


class ScriptResults(dict):
    """``{table: pydict-of-columns}`` plus the distributed-execution
    metadata as attributes — plain-dict compatible for existing callers.

    - ``partial``: True when >=1 planned data agent was lost and the
      tables cover only the survivors (graceful degradation)
    - ``missing_agents``: the lost agents' ids
    - ``qid`` / ``agent_stats``: execution identity + per-agent timings
    - ``predicted_cost``: pxbound's plan-time resource envelope
      (``bytes_staged_hi``/``rows_in_hi``/...; None entries =
      sketch-less, unbounded) — the broker's admission-control signal;
      compare with the observed usage in ``agent_stats``
    """

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.partial = False
        self.missing_agents: list = []
        self.missing_reasons: dict = {}
        # Why a partial result stopped early: "deadline" | "cancelled"
        # | None (agent loss keeps the per-agent missing_reasons only).
        self.interrupted: str | None = None
        self.qid = None
        self.agent_stats: dict = {}
        self.predicted_cost: dict | None = None
        # Resolved tenant the broker admitted the query under
        # (services/tenancy.py; "shared" for unscoped callers).
        self.tenant: str | None = None
        # Result staleness (storage-tier observability): worst scanned-
        # table watermark lag across agents at execute time, ms. 0 =
        # fresh or no time-indexed scan; None from pre-freshness brokers.
        self.freshness_lag_ms: float | None = None


class TableRecordHandler:
    """Row-wise consumer of one output table (pxapi TableRecordHandler)."""

    def handle_init(self, table_name: str, relation) -> None:  # noqa: B027
        pass

    def handle_record(self, record: dict) -> None:
        raise NotImplementedError

    def handle_done(self, table_name: str) -> None:  # noqa: B027
        pass


class Client:
    """Executes PxL scripts against a served broker."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6100,
                 connect_timeout_s: float = 10.0):
        from .services.netbus import RemoteBus

        self._bus = RemoteBus(host, port, connect_timeout_s=connect_timeout_s)

    def close(self) -> None:
        self._bus.close()

    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- introspection -------------------------------------------------------
    def list_scripts(self) -> list[str]:
        return self._request("broker.scripts", {})["scripts"]

    def debug_queries(self, limit: int = 50) -> dict:
        """Recent distributed-query traces from the broker — status,
        duration, and per-agent resource usage (bytes staged, device ms,
        wire bytes). The `px debug queries` surface."""
        res = self._request("broker.debug_queries", {"limit": limit})
        return {"in_flight": res.get("in_flight", []),
                "queries": res.get("queries", [])}

    def profile(
        self,
        agent: str | None = None,
        tenant: str | None = None,
        script: str | None = None,
        limit: int = 64,
    ) -> dict:
        """Cluster-merged folded-stack CPU profile from the broker
        (agents' heartbeat summaries + the broker's own sampler) —
        the `px profile` surface. Returns {"agents": [...], "stacks":
        [{stack, count, qid, script_hash, tenant, phase}, ...]} with
        optional agent / tenant / script-hash filters."""
        res = self._request("broker.profile", {
            "agent": agent or "", "tenant": tenant or "",
            "script": script or "", "limit": limit,
        })
        return {"agents": res.get("agents", []),
                "stacks": res.get("stacks", [])}

    def cancel_query(self, qid: str) -> bool:
        """Cooperatively cancel a running one-shot query (`px cancel`):
        the broker stops its agents at their next window boundary and
        the original caller receives a partial result with reason
        "cancelled". Returns whether a registered query was found."""
        return bool(
            self._request("broker.cancel", {"qid": qid}).get("cancelled")
        )

    def schemas(self) -> dict:
        return self._request("broker.schemas", {})["schemas"]

    def agents(self) -> list[dict]:
        return self._request("broker.agents", {})["agents"]

    def agents_status(self) -> dict:
        """Like :meth:`agents` but returns the full reply, including
        ``broker`` — WHICH broker replica answered (broker HA; empty on
        a plain single-broker deploy). The `px agents` surface."""
        res = self._request("broker.agents", {})
        return {"agents": res.get("agents", []),
                "broker": res.get("broker", "")}

    def resolve_leader(self, timeout_s: float = 2.0) -> dict:
        """Current broker-HA leader as every replica last saw it:
        ``{"broker": id, "epoch": n, "role": ..., "answered_by": id}``.
        Raises on a non-HA deploy (nobody serves ``broker.leader``)."""
        return self._request("broker.leader", {}, timeout_s=timeout_s)

    # -- execution -----------------------------------------------------------
    def execute_script(
        self,
        pxl: str,
        timeout_s: float = 30.0,
        max_output_rows: int = 10_000,
        handler_factory: Optional[Callable[[str], TableRecordHandler]] = None,
        require_complete: Optional[bool] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
        deadline_ms: Optional[float] = None,
    ):
        """Run a script; returns a ``ScriptResults``
        ({table: pydict-of-columns} with partial/missing_agents/qid/
        agent_stats attributes).

        With ``handler_factory``, each output table's rows additionally
        stream through a ``TableRecordHandler`` (the pxapi consumption
        model); the return value is unchanged. ``require_complete=True``
        fails instead of returning partial results when a data agent is
        lost mid-query.

        Multi-tenant scheduling: ``tenant`` scopes admission to that
        registered tenant's budget share (unknown names fold into the
        shared tenant), ``priority`` (higher first) and ``deadline_ms``
        order the broker's admission queue; a query past its deadline
        is shed while queued or returns ``partial`` with
        ``missing_reasons`` values ``"deadline"`` once dispatched.
        """
        req = {"query": pxl, "timeout_s": timeout_s,
               "max_output_rows": max_output_rows}
        if require_complete is not None:
            req["require_complete"] = bool(require_complete)
        if tenant is not None:
            req["tenant"] = str(tenant)
        if priority is not None:
            req["priority"] = int(priority)
        if deadline_ms is not None:
            req["deadline_ms"] = float(deadline_ms)
        from .services.msgbus import BusTimeout

        try:
            res = self._request(
                "broker.execute", req, timeout_s=timeout_s + 5,
            )
        except BusTimeout as e:
            # NEVER blind-retried: execute is non-idempotent (pxtrace
            # mutations; duplicate compute). Re-resolve the leader so
            # the structured error tells the caller where to resubmit.
            leader = ""
            try:
                info = self.resolve_leader()
                leader = str(info.get("broker", ""))
            except Exception:
                pass
            hint = (
                f" (current leader: {leader}; resubmit to it)"
                if leader else
                " (no broker leader answered; the cluster may be "
                "mid-failover)"
            )
            raise ScriptExecutionError(
                f"execute_script got no reply and was not retried "
                f"(non-idempotent){hint}: {e}"
            ) from e
        out = ScriptResults()
        out.partial = bool(res.get("partial"))
        out.missing_agents = list(res.get("missing_agents", []))
        out.missing_reasons = dict(res.get("missing_reasons", {}))
        out.interrupted = res.get("interrupted")
        out.qid = res.get("qid")
        out.agent_stats = dict(res.get("agent_stats", {}))
        out.predicted_cost = res.get("predicted_cost")
        out.tenant = res.get("tenant")
        out.freshness_lag_ms = res.get("freshness_lag_ms")
        for name, hb in sorted(res["tables"].items()):
            d = hb.to_pydict()
            out[name] = d
            if handler_factory is not None:
                h = handler_factory(name)
                h.handle_init(name, hb.relation)
                cols = list(d)
                for i in range(hb.length):
                    h.handle_record(
                        {c: _py(d[c][i]) for c in cols}
                    )
                h.handle_done(name)
        return out

    def stream_script(
        self,
        pxl: str,
        on_update: Callable[[dict], None],
        poll_interval_s: float = 0.25,
        require_complete: Optional[bool] = None,
    ) -> "StreamSubscription":
        """Subscribe to a live query (the reference's StreamResults /
        live-view flow): ``on_update`` receives
        {table, rows: pydict, seq, mode} as the cluster's tables grow —
        mode "append" carries only new rows, "replace" the full updated
        aggregate — until ``.cancel()``. Errors arrive as {error};
        a data agent lost mid-stream arrives as a
        {stream_degraded, partial, missing_agents} update (or as
        {error}, with ``require_complete=True``).
        """
        import uuid as _uuid

        topic = f"client.stream.{_uuid.uuid4().hex[:12]}"

        def _relay(msg):
            if "batch" in msg:
                hb = msg["batch"]
                on_update({
                    "table": msg.get("table"),
                    "rows": hb.to_pydict(),
                    "seq": msg.get("seq"),
                    "mode": msg.get("mode"),
                })
            else:
                on_update(msg)

        req = {"query": pxl, "update_topic": topic,
               "poll_interval_s": poll_interval_s}
        if require_complete is not None:
            req["require_complete"] = bool(require_complete)
        sub = self._bus.subscribe(topic, _relay)
        try:
            res = self._request("broker.execute_stream", req)
        except Exception:
            sub.unsubscribe()
            raise
        return StreamSubscription(self, res["qid"], sub)

    def _request(self, topic: str, msg: dict, timeout_s: float = 10.0) -> dict:
        import random as _random
        import time as _time

        from .config import get_flag
        from .services.msgbus import BusTimeout

        if get_flag("bus_secret") and "token" not in msg:
            from .services.auth import sign_token

            msg = {**msg, "token": sign_token(get_flag("bus_secret"), "api")}
        # Idempotent control-plane reads retry through a broker
        # failover window (capped exponential backoff + jitter);
        # anything else gets exactly one attempt.
        retries = (
            int(get_flag("client_request_retries"))
            if topic in _IDEMPOTENT_TOPICS else 0
        )
        base_s = max(float(get_flag("client_retry_backoff_ms")), 1.0) / 1e3
        attempt = 0
        while True:
            try:
                res = self._bus.request(topic, msg, timeout_s=timeout_s)
                break
            except BusTimeout:
                if attempt >= retries:
                    raise
                from .services.observability import default_counter

                default_counter(
                    "pixie_client_retries_total",
                    "Idempotent api.Client requests retried on BusTimeout",
                ).inc()
                backoff = min(base_s * (2 ** attempt), 2.0)
                _time.sleep(backoff * (1.0 + 0.25 * _random.random()))
                attempt += 1
        if not res.get("ok"):
            raise ScriptExecutionError(res.get("error", "unknown error"))
        return res


class StreamSubscription:
    """Client handle for a live query; ``cancel()`` ends it everywhere."""

    def __init__(self, client: Client, qid: str, sub):
        self.qid = qid
        self._client = client
        self._sub = sub

    def cancel(self) -> None:
        try:
            self._client._request("broker.stream_cancel", {"qid": self.qid})
        finally:
            if self._sub is not None:
                self._sub.unsubscribe()
                self._sub = None


def _py(v):
    return v.item() if hasattr(v, "item") else v
