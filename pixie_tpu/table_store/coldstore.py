"""Encoded cold storage tier: immutable compressed column windows.

The source table is explicitly hot/cold (``table/table.h:104``); the hot
ring (``table.py`` backends) holds raw fixed-width slabs sized for
zero-conversion staging, while this module holds the *demoted* tail of
the table as immutable encoded windows:

- **delta**    — monotonic non-decreasing int64 planes (``time_``, sorted
  row-id-like columns): first value + diffs downcast to the narrowest
  unsigned width that fits the largest diff.
- **rle**      — low-NDV numerics: (run values, run lengths) pairs kept
  only when they beat the raw slab by 2x.
- **dict**     — formalizes the existing string-id coding: string columns
  arrive as int32 dictionary codes already (``types/strings.py``), so the
  cold form is the code plane rebased to the narrowest unsigned width.
  Also applied to narrow-range integer planes.
- **raw**      — verbatim copy fallback; never worse than the hot slab.

Decode is bit-exact: ``decode()`` returns the original dtype and values,
so hot-vs-cold scans are bit-identical by construction (tested in
``tests/test_storage_tier.py``). Windows are immutable after
``append_window`` — readers decode without holding the store lock.

Decode attribution: decoding runs on whatever thread stages the window —
under the ``WindowPipeline`` that is the prefetch producer thread, which
is exactly what overlaps decompression with device compute
(decode-on-stage). A thread-local meter accumulates (seconds, bytes) per
decode so the engine can fold per-query ``decode_ms`` out of the
producer thread without touching query-scoped state (the producer thread
has no ``_QueryScratch``).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

#: Run-length encoding must beat raw by this factor to be chosen (the
#: decode pass costs a ``np.repeat``; a marginal win is not worth it).
_RLE_GAIN = 2.0

# -- thread-local decode meter -----------------------------------------------

_METER = threading.local()


def take_decode_meter() -> tuple[float, int]:
    """Return and reset this thread's (decode seconds, decoded raw bytes)
    accumulated since the last take. The staging generators call this
    after each window so decode time lands in the per-query trace even
    though decoding happens on the pipeline producer thread."""
    out = (getattr(_METER, "secs", 0.0), getattr(_METER, "nbytes", 0))
    _METER.secs = 0.0
    _METER.nbytes = 0
    return out


def _meter_add(secs: float, nbytes: int) -> None:
    _METER.secs = getattr(_METER, "secs", 0.0) + secs
    _METER.nbytes = getattr(_METER, "nbytes", 0) + nbytes


# -- plane encodings ----------------------------------------------------------


def _narrowest_uint(hi: int) -> np.dtype:
    for d in (np.uint8, np.uint16, np.uint32):
        if hi <= np.iinfo(d).max:
            return np.dtype(d)
    return np.dtype(np.uint64)


@dataclass(frozen=True)
class EncodedPlane:
    """One immutable encoded column plane of a cold window."""

    kind: str  # 'raw' | 'delta' | 'rle' | 'dict'
    dtype: np.dtype  # decoded dtype
    n: int
    data: tuple  # kind-specific ndarrays / scalars

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.data if isinstance(a, np.ndarray))

    def decode(self) -> np.ndarray:
        if self.kind == "raw":
            return self.data[0]
        if self.kind == "delta":
            first, diffs = self.data
            out = np.empty(self.n, dtype=np.int64)
            np.cumsum(diffs, dtype=np.int64, out=out)
            out += first
            if self.dtype.kind == "u":  # exact mod-2^64 reinterpret
                return out.view(self.dtype)
            return out.astype(self.dtype, copy=False)
        if self.kind == "rle":
            values, lengths = self.data
            return np.repeat(values, lengths)
        if self.kind == "dict":
            codes, base = self.data
            return (codes.astype(np.int64) + base).astype(self.dtype)
        raise ValueError(f"unknown encoding {self.kind!r}")


def encode_plane(p: np.ndarray, monotonic_hint: bool = False) -> EncodedPlane:
    """Pick the cheapest lossless encoding for one column plane."""
    n = len(p)
    dt = p.dtype
    raw = EncodedPlane("raw", dt, n, (np.ascontiguousarray(p),))
    if n < 2 or dt.kind not in "iu":
        return raw
    # delta: monotonic int64-ish planes (time_, sorted ids). diffs fit a
    # narrow unsigned width when the plane is smooth. Arithmetic runs in
    # the int64-wrapped domain (exact mod 2^64, so uint64 planes round-
    # trip bit-exactly) — but ONLY when every wrapped diff is >= 0: a
    # negative wrapped diff (true step > int64 max) would lose its high
    # bits in the narrow downcast.
    if dt.itemsize == 8 and (monotonic_hint or bool(np.all(p[1:] >= p[:-1]))):
        if bool(np.all(p[1:] >= p[:-1])):
            p64 = p.view(np.int64) if dt.kind == "u" else p.astype(np.int64)
            diffs = np.diff(p64, prepend=p64[:1])
            hi = int(diffs.max()) if n else 0
            if int(diffs.min()) >= 0:
                narrow = _narrowest_uint(hi)
                if narrow.itemsize < dt.itemsize:
                    return EncodedPlane(
                        "delta", dt, n, (p64[0], diffs.astype(narrow)),
                    )
    # rle: low-NDV planes compress to (values, lengths) runs.
    change = np.nonzero(p[1:] != p[:-1])[0]
    n_runs = len(change) + 1
    rle_bytes = n_runs * (dt.itemsize + 4)
    if rle_bytes * _RLE_GAIN <= p.nbytes:
        starts = np.concatenate(([0], change + 1))
        lengths = np.diff(np.concatenate((starts, [n]))).astype(np.int32)
        return EncodedPlane("rle", dt, n, (p[starts].copy(), lengths))
    # dict/rebase: narrow-range integers (string dictionary codes are
    # int32 with a small id space — this is the formalized cold form).
    lo, hi = int(p.min()), int(p.max())
    if hi > np.iinfo(np.int64).max:  # uint64 beyond int64: rebase overflows
        return raw
    narrow = _narrowest_uint(hi - lo)
    if narrow.itemsize < dt.itemsize:
        return EncodedPlane(
            "dict", dt, n, ((p.astype(np.int64) - lo).astype(narrow), np.int64(lo))
        )
    return raw


# -- cold windows --------------------------------------------------------------


@dataclass(frozen=True)
class ColdWindow:
    """An immutable encoded run of rows [row0, row0 + n)."""

    row0: int
    n: int
    min_time: int
    max_time: int
    planes: tuple  # EncodedPlane per table plane, layout order
    nbytes: int  # encoded
    raw_nbytes: int  # decoded (hot-slab) size

    @property
    def end_row(self) -> int:
        return self.row0 + self.n


class ColdStoreError(RuntimeError):
    """A cold window failed to decode (corruption / internal bug). Raised
    from the staging path so it propagates through the pipeline like any
    stage error."""


class ColdStore:
    """Ordered, byte-budgeted collection of encoded cold windows.

    Windows are appended at the hot boundary (demotion) and evicted from
    the front (true expiry). All mutation happens under ``lock``;
    readers snapshot the window list under the lock and decode outside
    it (windows are immutable).
    """

    def __init__(self, has_time: bool):
        self.has_time = has_time
        self.lock = threading.Lock()
        self.windows: list[ColdWindow] = []
        self.nbytes = 0
        self.raw_nbytes = 0
        # lifetime counters (monotonic; exported via Table.stats())
        self.demotions = 0  # windows ever demoted into the store
        self.evictions = 0  # windows ever evicted (true expiry)
        self.rows_evicted = 0
        self.bytes_evicted_raw = 0
        self.decoded_windows = 0
        self.decoded_bytes = 0
        self.decode_seconds = 0.0

    # -- write side (tier.py only) -------------------------------------------
    def append_window(
        self, row0: int, planes: Sequence[np.ndarray], min_t: int, max_t: int,
        monotonic_planes: Sequence[bool],
    ) -> ColdWindow:
        enc = tuple(
            encode_plane(p, monotonic_hint=m)
            for p, m in zip(planes, monotonic_planes)
        )
        n = len(planes[0])
        win = ColdWindow(
            row0=row0, n=n, min_time=min_t, max_time=max_t, planes=enc,
            nbytes=sum(e.nbytes for e in enc),
            raw_nbytes=sum(p.nbytes for p in planes),
        )
        with self.lock:
            if self.windows and row0 != self.windows[-1].end_row:
                raise ColdStoreError(
                    f"non-contiguous demotion: window row0={row0} but cold "
                    f"tier ends at {self.windows[-1].end_row}"
                )
            self.windows.append(win)
            self.nbytes += win.nbytes
            self.raw_nbytes += win.raw_nbytes
            self.demotions += 1
        return win

    def evict_to(self, budget_bytes: int) -> int:
        """Evict oldest windows until encoded bytes fit the budget.
        THIS is expiry: rows leave the table for good and the eviction
        counters (which feed ``rows_expired``/``bytes_expired``) move."""
        evicted = 0
        with self.lock:
            while self.windows and self.nbytes > budget_bytes:
                w = self.windows.pop(0)
                self.nbytes -= w.nbytes
                self.raw_nbytes -= w.raw_nbytes
                self.evictions += 1
                self.rows_evicted += w.n
                self.bytes_evicted_raw += w.raw_nbytes
                evicted += 1
        return evicted

    # -- read side -----------------------------------------------------------
    def _snapshot(self) -> list[ColdWindow]:
        with self.lock:
            return list(self.windows)

    def first_row_id(self) -> Optional[int]:
        with self.lock:
            return self.windows[0].row0 if self.windows else None

    def end_row_id(self) -> Optional[int]:
        with self.lock:
            return self.windows[-1].end_row if self.windows else None

    def min_time(self) -> Optional[int]:
        with self.lock:
            return self.windows[0].min_time if self.windows else None

    def num_rows(self) -> int:
        with self.lock:
            return sum(w.n for w in self.windows)

    def _decode_window(self, w: ColdWindow) -> list[np.ndarray]:
        t0 = time.perf_counter()
        try:
            planes = [e.decode() for e in w.planes]
        except ColdStoreError:
            raise
        except Exception as e:  # corrupt window must fail the query loudly
            raise ColdStoreError(
                f"cold window [{w.row0}, {w.end_row}) failed to decode: {e!r}"
            ) from e
        for e, p in zip(w.planes, planes):
            if len(p) != w.n or p.dtype != e.dtype:
                raise ColdStoreError(
                    f"cold window [{w.row0}, {w.end_row}) decoded to "
                    f"{len(p)} rows of {p.dtype}, expected {w.n} of {e.dtype}"
                )
        dt = time.perf_counter() - t0
        with self.lock:
            self.decoded_windows += 1
            self.decoded_bytes += w.raw_nbytes
            self.decode_seconds += dt
        _meter_add(dt, w.raw_nbytes)
        return planes

    def read(self, start_row_id: int, max_rows: int):
        """Mirror of the backend ``read`` ABI over the cold tier:
        returns (planes, first_row_id, n) for rows in
        [start_row_id, start_row_id + max_rows) that live cold."""
        wins = self._snapshot()
        pieces: list[list[np.ndarray]] = []
        first = None
        copied = 0
        for w in wins:
            if w.end_row <= start_row_id:
                continue
            lo = max(start_row_id, w.row0)
            if first is None:
                first = lo
            elif w.row0 != first + copied:
                break  # non-contiguous (should not happen; be safe)
            take = min(w.end_row - lo, max_rows - copied)
            if take <= 0:
                break
            s = lo - w.row0
            planes = self._decode_window(w)
            pieces.append([p[s : s + take] for p in planes])
            copied += take
            if copied >= max_rows:
                break
        if not pieces:
            return [], start_row_id, 0
        if len(pieces) == 1:
            out = pieces[0]
        else:
            out = [
                np.concatenate([ps[i] for ps in pieces])
                for i in range(len(pieces[0]))
            ]
        return out, first, copied

    def row_id_for_time(self, t: int, strictly_greater: bool) -> Optional[int]:
        """First cold row id with time >= t (> when strict), or None when
        every cold row is older (caller falls through to the hot ring).
        Times are plane 0 by the table layout convention."""
        if not self.has_time:
            return None
        for w in self._snapshot():
            hit = (w.max_time > t) if strictly_greater else (w.max_time >= t)
            if not hit:
                continue
            times = self._decode_window(w)[0]
            idx = np.nonzero(times > t if strictly_greater else times >= t)[0]
            if len(idx):
                return w.row0 + int(idx[0])
        return None
