"""Hot/cold Table with time-indexed cursors and byte-budget expiry.

Reference parity: ``src/table_store/table/table.h:104`` — writes land in a
hot store, a compaction pass merges them into large cold slabs, reads go
through a ``Cursor`` keyed by *unique row ids* so no row is returned twice
even when compaction/expiry runs mid-query, and the oldest batches expire
when the byte budget is exceeded.

TPU-first redesign: both stores hold flat fixed-width column slabs (no
Arrow framing) sized so cursor reads hand back contiguous windows that
stage straight into fixed-capacity device buffers. Strings are dictionary
ids by the time they reach the table (``pixie_tpu.types.strings``); the
dictionaries live on the Python Table wrapper and are append-only, so
shared references stay valid as the table grows.

The slab store itself is native C++ (``pixie_tpu/native/table_ring.cc``,
ctypes-bound) with a pure-numpy fallback mirroring the same ABI.
"""

from __future__ import annotations

import ctypes
import threading
import time
from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

import numpy as np

from ..native import load as load_native
from ..types.batch import HostBatch
from ..types.dtypes import DataType, host_dtypes
from ..types.relation import Relation
from ..types.strings import StringDictionary

TIME_COLUMN = "time_"
DEFAULT_COMPACTED_ROWS = 64 * 1024

#: EWMA smoothing factor for the per-append ingest rate: ~the last five
#: appends dominate, so the rate reflects the current push cadence
#: rather than table-lifetime throughput.
INGEST_EWMA_ALPHA = 0.2


@dataclass
class TableStats:
    bytes: int
    hot_bytes: int
    cold_bytes: int
    num_batches: int
    batches_added: int
    batches_expired: int
    bytes_added: int
    compacted_batches: int
    min_time: int
    num_rows: int
    # -- freshness surface (storage-tier observability) ----------------------
    # Derived/maintained OUTSIDE the backend stats buffer (the native ABI
    # stays 10 slots): monotonic append/expiry counters come from the
    # backend's existing row-id space (row ids are never reused, so
    # end_row_id == rows ever appended and first_row_id == rows expired),
    # the watermark from the col_stats bounds the append path already
    # maintains, and the wall-clock/EWMA fields from two attribute writes
    # per append. Defaults let bare positional constructions keep working.
    rows_added: int = 0  # rows ever appended (monotonic)
    rows_expired: int = 0  # rows dropped by TRUE expiry (monotonic)
    bytes_expired: int = 0  # raw bytes lost to true expiry (monotonic)
    watermark: int = -1  # max event-time ns ever appended (never regresses)
    last_append_unix_ns: int = 0  # wall time of the latest append
    ingest_rows_per_s: float = 0.0  # per-append EWMA ingest rate
    device_bytes: int = 0  # device-resident (HBM) staged window bytes
    # -- storage tier surface (table_store/tier.py; zeros when untiered).
    # For a tiered table hot_bytes/cold_bytes above are repurposed as the
    # per-TIER split (whole ring = hot, encoded store = cold) rather
    # than the ring's internal hot/compacted split.
    hot_rows: int = 0  # live rows in the hot ring
    cold_rows: int = 0  # live rows in the encoded cold store
    cold_raw_bytes: int = 0  # decoded size of the cold rows (ratio base)
    cold_windows: int = 0
    demotions: int = 0  # windows ever demoted hot -> cold (monotonic)
    evictions: int = 0  # cold windows ever evicted = expired (monotonic)
    decode_seconds: float = 0.0  # lifetime cold decode wall time


@dataclass(frozen=True)
class StartSpec:
    """Where a cursor begins: at a time, or the current start of table."""

    start_time: Optional[int] = None

    @classmethod
    def at_time(cls, t: int) -> "StartSpec":
        return cls(start_time=t)


@dataclass(frozen=True)
class StopSpec:
    """When a cursor is exhausted: at a time, at the current end of the
    table, or never (infinite streaming — the live-query mode)."""

    stop_time: Optional[int] = None
    infinite: bool = False

    @classmethod
    def at_time(cls, t: int) -> "StopSpec":
        return cls(stop_time=t)

    @classmethod
    def current_end(cls) -> "StopSpec":
        return cls()

    @classmethod
    def never(cls) -> "StopSpec":
        return cls(infinite=True)


class _PyBackend:
    """Pure-numpy mirror of the native slab store ABI (fallback path)."""

    def __init__(self, elem_dtypes, has_time, compacted_rows, max_bytes):
        self.elem_dtypes = elem_dtypes
        self.row_bytes = sum(np.dtype(d).itemsize for d in elem_dtypes)
        self.has_time = has_time
        self.compacted_rows = compacted_rows
        self.max_bytes = max_bytes
        self.lock = threading.Lock()
        self.hot: list = []  # [first_row_id, planes, min_t, max_t]
        self.cold: list = []
        self.next_row_id = 0
        self.counters = dict(
            batches_added=0, batches_expired=0, bytes_added=0, compacted=0
        )

    def _bytes(self, q) -> int:
        return sum(len(b[1][0]) * self.row_bytes for b in q)

    def _first_row_id(self) -> int:
        if self.cold:
            return self.cold[0][0]
        if self.hot:
            return self.hot[0][0]
        return self.next_row_id

    def append(self, planes: Sequence[np.ndarray], times) -> int:
        n = len(planes[0])
        if n == 0:
            return -1
        mn, mx = (int(times.min()), int(times.max())) if self.has_time else (0, 0)
        with self.lock:
            if self.max_bytes >= 0:
                while (
                    self._bytes(self.hot) + self._bytes(self.cold) + n * self.row_bytes
                    > self.max_bytes
                ):
                    q = self.cold if self.cold else self.hot
                    if not q:
                        break
                    q.pop(0)
                    self.counters["batches_expired"] += 1
            rid = self.next_row_id
            self.next_row_id += n
            self.hot.append([rid, [p.copy() for p in planes], mn, mx])
            self.counters["batches_added"] += 1
            self.counters["bytes_added"] += n * self.row_bytes
            return rid

    def compact(self) -> int:
        with self.lock:
            created = 0
            while self.hot:
                rows, take = 0, 0
                while take < len(self.hot) and rows < self.compacted_rows:
                    rows += len(self.hot[take][1][0])
                    take += 1
                group = self.hot[:take]
                del self.hot[:take]
                planes = [
                    np.concatenate([g[1][i] for g in group])
                    for i in range(len(self.elem_dtypes))
                ]
                self.cold.append(
                    [
                        group[0][0],
                        planes,
                        min(g[2] for g in group),
                        max(g[3] for g in group),
                    ]
                )
                self.counters["compacted"] += 1
                created += 1
            return created

    def first_row_id(self) -> int:
        with self.lock:
            return self._first_row_id()

    def end_row_id(self) -> int:
        with self.lock:
            return self.next_row_id

    def row_id_for_time(self, t: int, strictly_greater: bool) -> int:
        with self.lock:
            if not self.has_time:
                return self._first_row_id()
            for q in (self.cold, self.hot):
                for rid, planes, _, mx in q:
                    if (mx > t) if strictly_greater else (mx >= t):
                        times = planes[0]
                        hits = np.nonzero(times > t if strictly_greater else times >= t)[0]
                        if len(hits):
                            return rid + int(hits[0])
            return self.next_row_id

    def read(self, start_row_id: int, max_rows: int):
        with self.lock:
            row_id = max(start_row_id, self._first_row_id())
            pieces = [[] for _ in self.elem_dtypes]
            copied = 0
            for q in (self.cold, self.hot):
                for rid, planes, _, _ in q:
                    n = len(planes[0])
                    if rid + n <= row_id:
                        continue
                    start = max(0, row_id + copied - rid)
                    take = min(n - start, max_rows - copied)
                    if take <= 0:
                        continue
                    for i, p in enumerate(planes):
                        pieces[i].append(p[start : start + take])
                    copied += take
                    if copied >= max_rows:
                        break
                if copied >= max_rows:
                    break
            out = [
                np.concatenate(ps) if ps else np.empty(0, dtype=d)
                for ps, d in zip(pieces, self.elem_dtypes)
            ]
            return out, row_id, copied

    def drop_before(self, row_id: int) -> int:
        """Drop rows with id < row_id (cold-tier demotion handoff — NOT
        expiry: batches_expired does not move). Row-granular: a batch
        straddling row_id is split and its tail kept."""
        with self.lock:
            for q in (self.cold, self.hot):
                while q:
                    rid, planes, mn, mx = q[0]
                    n = len(planes[0])
                    if rid + n <= row_id:
                        q.pop(0)
                        continue
                    if rid < row_id:
                        drop = row_id - rid
                        tail = [p[drop:].copy() for p in planes]
                        if self.has_time:
                            mn = int(tail[0].min())
                            mx = int(tail[0].max())
                        q[0] = [row_id, tail, mn, mx]
                    return self._first_row_id()
            return self._first_row_id()

    def stats(self) -> list:
        with self.lock:
            hot_b, cold_b = self._bytes(self.hot), self._bytes(self.cold)
            min_t = (
                self.cold[0][2] if self.cold else (self.hot[0][2] if self.hot else -1)
            )
            return [
                hot_b + cold_b,
                hot_b,
                cold_b,
                len(self.hot) + len(self.cold),
                self.counters["batches_added"],
                self.counters["batches_expired"],
                self.counters["bytes_added"],
                self.counters["compacted"],
                min_t,
                self.next_row_id - self._first_row_id(),
            ]


class _NativeBackend:
    """ctypes binding for pixie_tpu/native/table_ring.cc."""

    _configured = False

    def __init__(self, lib, elem_dtypes, has_time, compacted_rows, max_bytes):
        self.lib = lib
        self.elem_dtypes = [np.dtype(d) for d in elem_dtypes]
        self.has_time = has_time
        self._configure(lib)
        sizes = (ctypes.c_int32 * len(self.elem_dtypes))(
            *[d.itemsize for d in self.elem_dtypes]
        )
        self.handle = lib.pxt_table_create(
            len(self.elem_dtypes), sizes, int(has_time), compacted_rows, max_bytes
        )

    @classmethod
    def _configure(cls, lib):
        if getattr(lib, "_pxt_configured", False):
            return
        lib.pxt_table_create.restype = ctypes.c_void_p
        lib.pxt_table_create.argtypes = [
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.pxt_table_destroy.argtypes = [ctypes.c_void_p]
        lib.pxt_table_append.restype = ctypes.c_int64
        lib.pxt_table_append.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64),
        ]
        for fn in ("pxt_table_compact", "pxt_table_first_row_id", "pxt_table_end_row_id"):
            f = getattr(lib, fn)
            f.restype = ctypes.c_int64
            f.argtypes = [ctypes.c_void_p]
        lib.pxt_table_row_id_for_time.restype = ctypes.c_int64
        lib.pxt_table_row_id_for_time.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int32,
        ]
        lib.pxt_table_read.restype = ctypes.c_int64
        lib.pxt_table_read.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.pxt_table_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.pxt_table_drop_before.restype = ctypes.c_int64
        lib.pxt_table_drop_before.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib._pxt_configured = True

    def __del__(self):
        if getattr(self, "handle", None):
            self.lib.pxt_table_destroy(self.handle)
            self.handle = None

    def append(self, planes: Sequence[np.ndarray], times) -> int:
        planes = [np.ascontiguousarray(p) for p in planes]
        n = len(planes[0])
        ptrs = (ctypes.c_void_p * len(planes))(*[p.ctypes.data for p in planes])
        tptr = None
        if self.has_time:
            times = np.ascontiguousarray(times, dtype=np.int64)
            tptr = times.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        return self.lib.pxt_table_append(self.handle, n, ptrs, tptr)

    def compact(self) -> int:
        return self.lib.pxt_table_compact(self.handle)

    def first_row_id(self) -> int:
        return self.lib.pxt_table_first_row_id(self.handle)

    def end_row_id(self) -> int:
        return self.lib.pxt_table_end_row_id(self.handle)

    def row_id_for_time(self, t: int, strictly_greater: bool) -> int:
        return self.lib.pxt_table_row_id_for_time(self.handle, t, int(strictly_greater))

    def read(self, start_row_id: int, max_rows: int):
        out = [np.empty(max_rows, dtype=d) for d in self.elem_dtypes]
        ptrs = (ctypes.c_void_p * len(out))(*[a.ctypes.data for a in out])
        first = ctypes.c_int64(0)
        n = self.lib.pxt_table_read(
            self.handle, start_row_id, max_rows, ptrs, ctypes.byref(first)
        )
        return [a[:n] for a in out], first.value, n

    def drop_before(self, row_id: int) -> int:
        return self.lib.pxt_table_drop_before(self.handle, row_id)

    def stats(self) -> list:
        buf = (ctypes.c_int64 * 10)()
        self.lib.pxt_table_stats(self.handle, buf)
        return list(buf)


class Cursor:
    """Iterates a Table without ever returning a row twice.

    Reference: ``table.h`` Table::Cursor — position is the unique id of the
    next unread row, so compaction (which moves rows between stores) and
    expiry (which drops them) never desynchronize the read position.
    """

    def __init__(self, table: "Table", start: StartSpec, stop: StopSpec):
        self._table = table
        if start.start_time is not None:
            self._next_row_id = table.row_id_for_time(start.start_time, False)
        else:
            self._next_row_id = table.first_row_id()
        self.update_stop_spec(stop)

    def update_stop_spec(self, stop: StopSpec) -> None:
        t = self._table
        if stop.infinite:
            self._stop_row_id = None
        elif stop.stop_time is not None:
            # Stop at the time or the current end, whichever is first
            # (reference StopAtTime semantics).
            self._stop_row_id = min(
                t.row_id_for_time(stop.stop_time, True), t.end_row_id()
            )
        else:
            self._stop_row_id = t.end_row_id()

    def done(self) -> bool:
        if self._stop_row_id is None:
            return False
        return self._next_row_id >= self._stop_row_id

    def next_batch_ready(self) -> bool:
        if self._stop_row_id is not None:
            return not self.done()
        return self._next_row_id < self._table.end_row_id()

    def skip_to(self, row_id: int) -> None:
        """Advance past rows a zone-map check proved irrelevant (the
        scan-skip fast-forward; never moves backwards)."""
        self._next_row_id = max(self._next_row_id, int(row_id))

    def next_batch(self, max_rows: int, cols: Optional[Sequence[str]] = None):
        """Read up to max_rows as a HostBatch, or None when exhausted/dry."""
        if self.done():
            return None
        if self._stop_row_id is not None:
            max_rows = min(max_rows, self._stop_row_id - self._next_row_id)
        planes, first, n = self._table.read_rows(self._next_row_id, max_rows)
        if self._stop_row_id is not None:
            # Expiry may have skipped the read past the stop snapshot.
            n = min(n, max(0, self._stop_row_id - first))
            planes = [p[:n] for p in planes]
        if n == 0:
            self._next_row_id = max(self._next_row_id, first)
            return None
        self._next_row_id = first + n
        return self._table._batch_from_planes(planes, cols)


class Table:
    """Engine-facing table: relation + dictionaries over the slab store."""

    def __init__(
        self,
        name: str,
        relation: Relation | None = None,
        max_bytes: int = -1,
        compacted_rows: int = DEFAULT_COMPACTED_ROWS,
        dicts: dict[str, StringDictionary] | None = None,
    ):
        self.name = name
        self.relation = relation or Relation()
        # ``dicts`` may be shared across tablets of one logical table so
        # every tablet encodes strings into the same id space.
        self.dicts: dict[str, StringDictionary] = dicts if dicts is not None else {}
        self.max_bytes = max_bytes
        self.compacted_rows = compacted_rows
        self._backend = None
        self._plane_layout: list[tuple[str, int]] = []  # native order
        # Device residency (HBM as cold store): staged windows + watermark
        # of rows already staged at append time (device_cache.py). The
        # staging window size is a per-table fact: it defaults to the
        # window_rows flag and is ADOPTED from the first consumer that
        # scans at a different size, so append-time staging and query
        # windows converge without env-var choreography.
        from ..config import get_flag as _get_flag

        self._device_cache = None
        self._staged_through = 0
        self.device_window_rows = int(_get_flag("window_rows"))
        # Mesh residency: when a DistributedEngine owns the table, staged
        # windows device_put row-sharded over its mesh (None = single
        # device), padded to a shard-count multiple.
        self.stage_sharding = None
        self.stage_capacity_multiple = 1
        # Per-column (min, max) over every row ever appended, for
        # single-plane integer columns. Conservative bounds (ring expiry
        # never widens them), maintained on the push path so the query
        # compiler can pick dense-domain group-bys for integer keys the
        # way it does for dictionary codes. The reference has no analog
        # (its agg hash map is domain-oblivious, agg_node.h).
        self.col_stats: dict[str, tuple[int, int]] = {}
        # Ingest sketches (sketches.py): per-key-column HLL NDV + zone
        # maps + row count, consulted by join routing and the planner's
        # eager-aggregation sizing (PAPERS.md 2102.02440). Gated by the
        # ingest_sketches flag; None until the first sketched append.
        self.sketches = None
        # Freshness bookkeeping (storage-tier observability): wall time
        # of the latest append + a per-append ingest-rate EWMA. Plain
        # attribute writes on the push path — same unlocked-wrapper
        # convention as col_stats/sketches above (the backend holds the
        # only append-path lock); readers snapshot via stats().
        self._last_append_unix_ns = 0
        self._last_append_mono = None
        self._last_append_rows = 0
        self._ingest_ewma = 0.0
        # Cold storage tier (tier.py): set by _init_backend when the
        # cold_tier_mb flag is on AND the table is byte-bounded. A
        # tiered table's backend ring is created UNBOUNDED — the tier
        # manager owns both budgets (demote past max_bytes, evict past
        # cold_tier_mb), so ring self-expiry never races the demotion
        # handoff.
        self._tier = None
        if len(self.relation):
            self._init_backend()

    # -- backend wiring ------------------------------------------------------
    def _init_backend(self) -> None:
        has_time = (
            self.relation.has_column(TIME_COLUMN)
            and self.relation.col_type(TIME_COLUMN) == DataType.TIME64NS
        )
        # Native layout: the time plane first (the native time index reads
        # column 0), then every remaining plane in relation order.
        layout: list[tuple[str, int]] = []
        if has_time:
            layout.append((TIME_COLUMN, 0))
        for cname, dt in self.relation.items():
            for i in range(len(host_dtypes(dt))):
                if (cname, i) != (TIME_COLUMN, 0) or not has_time:
                    layout.append((cname, i))
        self._plane_layout = layout
        dts = [
            np.dtype(host_dtypes(self.relation.col_type(c))[i]) for c, i in layout
        ]
        from ..config import get_flag as _get_flag

        cold_mb = int(_get_flag("cold_tier_mb"))
        tiered = cold_mb > 0 and self.max_bytes >= 0
        lib = load_native("table_ring")
        ring_max = -1 if tiered else self.max_bytes
        args = (dts, has_time, self.compacted_rows, ring_max)
        self._backend = (
            _NativeBackend(lib, *args) if lib is not None else _PyBackend(*args)
        )
        if tiered:
            from .tier import MB, TierManager

            self._tier = TierManager(self, self.max_bytes, cold_mb * MB)
        for cname, dt in self.relation.items():
            if dt == DataType.STRING:
                self.dicts.setdefault(cname, StringDictionary())

    # -- write path ----------------------------------------------------------
    def append(self, data, time_cols: Iterable[str] = (TIME_COLUMN,)) -> HostBatch:
        """Push path: Stirling's TransferRecordBatch analog (table.h:268)."""
        hb = (
            data
            if isinstance(data, HostBatch)
            else HostBatch.from_pydict(
                data,
                relation=self.relation if len(self.relation) else None,
                time_cols=tuple(time_cols),
                dicts=self.dicts,
            )
        )
        if not len(self.relation):
            self.relation = hb.relation
            self._init_backend()
        if hb.length == 0:
            return hb
        cols = dict(hb.cols)  # never mutate the caller's batch
        for col, d in hb.dicts.items():
            if col not in self.dicts:
                self.dicts[col] = d
            elif self.dicts[col] is not d:
                # Re-encode foreign ids into this table's dictionary,
                # extending it in place (append-only: ids already handed
                # out in earlier batches stay valid).
                mine = self.dicts[col]
                remap = np.fromiter(
                    (mine.get_or_add(s) for s in d.strings),
                    dtype=np.int32,
                    count=len(d),
                )
                ids = cols[col][0]
                cols[col] = (
                    np.where(ids >= 0, remap[np.clip(ids, 0, None)], -1).astype(
                        np.int32
                    ),
                )
        planes = [np.ascontiguousarray(cols[c][i]) for c, i in self._plane_layout]
        for (c, _i), p in zip(self._plane_layout, planes):
            if p.ndim != 1 or len(p) != hb.length:
                # A mis-shaped plane would silently corrupt the flat slab.
                raise ValueError(
                    f"column {c!r} plane has shape {p.shape}; expected "
                    f"1-D of length {hb.length}"
                )
        for (c, i), p in zip(self._plane_layout, planes):
            if (
                i == 0
                and len(p)
                and self.relation.col_type(c)
                in (DataType.INT64, DataType.TIME64NS)
            ):
                lo, hi = int(p.min()), int(p.max())
                cur = self.col_stats.get(c)
                self.col_stats[c] = (
                    (lo, hi)
                    if cur is None
                    else (min(cur[0], lo), max(cur[1], hi))
                )
        times = cols[TIME_COLUMN][0] if (TIME_COLUMN, 0) == self._plane_layout[0] else None
        if self._tier is not None:
            # Make room BEFORE the append lands: oldest windows demote
            # (encode-then-drop handoff, not expiry) so the unbounded
            # ring never holds more than max_bytes after this append.
            self._tier.demote_for(sum(p.nbytes for p in planes))
        rid = self._backend.append(planes, times)
        if rid >= 0:
            self._note_append_freshness(hb.length)
        from ..config import get_flag

        if (
            get_flag("ingest_sketches") and rid >= 0
            and not self.name.startswith("__")
        ):
            # Per-column NDV/zone-map sketches for join routing: the
            # single-plane INT64 columns col_stats already bounds, plus
            # dictionary string code planes (their ids ARE the join key
            # space). time_ is skipped — the time index supersedes it.
            # Dunder telemetry tables are excluded: they are never join
            # build sides, their bounds path is the documented
            # sketch-less fallback, and sketching a dozen INT64 columns
            # per __tables__/__queries__ fold row taxed every finished
            # trace AND bloated the bounds-memo stats key.
            if self.sketches is None:
                from .sketches import TableSketches

                self.sketches = TableSketches()
            self.sketches.rows += hb.length
            for (c, i), p in zip(self._plane_layout, planes):
                if i != 0 or c == TIME_COLUMN or len(p) == 0:
                    continue
                if self.relation.col_type(c) in (
                    DataType.INT64, DataType.STRING
                ) and len(host_dtypes(self.relation.col_type(c))) == 1:
                    self.sketches.update(c, p, rid)

        if get_flag("device_residency"):
            # Ship any newly completed windows to device now (the
            # device_put is async) so queries find them resident.
            self.stage_resident()
        return hb

    def _note_append_freshness(self, n: int) -> None:
        """Freshness bookkeeping per appended batch: two clock reads +
        EWMA arithmetic (the watermark itself is the ``time_`` col_stats
        bound append already maintains — no extra min/max pass). A
        separate method so the append-overhead A/B test can strip
        exactly this addition."""
        self._last_append_unix_ns = time.time_ns()
        self._last_append_rows = n
        mono = time.monotonic()
        prev, self._last_append_mono = self._last_append_mono, mono
        if prev is not None and mono > prev:
            rate = n / (mono - prev)
            self._ingest_ewma += (
                INGEST_EWMA_ALPHA * (rate - self._ingest_ewma)
            )

    def compact(self) -> int:
        """CompactHotToCold analog; call periodically (service loop)."""
        return self._backend.compact()

    # -- tier-merged row-id space --------------------------------------------
    # One unique monotone row-id space spans both tiers: demotion moves a
    # row from the ring into the cold store WITHOUT changing its id, so
    # cursors/watermarks keyed by row id never re-read or skip across a
    # demotion. These helpers are the read-path entry points; everything
    # below the Cursor goes through them instead of the bare backend.

    def first_row_id(self) -> int:
        """Oldest LIVE row id across both tiers. Advances only on true
        expiry (cold eviction for tiered tables, ring expiry otherwise)."""
        if self._tier is not None:
            f = self._tier.store.first_row_id()
            if f is not None:
                return f
        return self._backend.first_row_id()

    def end_row_id(self) -> int:
        return self._backend.end_row_id()

    def row_id_for_time(self, t: int, strictly_greater: bool) -> int:
        if self._tier is not None:
            store = self._tier.store
            if not store.has_time:
                return self.first_row_id()
            r = store.row_id_for_time(t, strictly_greater)
            if r is not None:
                return r
        return self._backend.row_id_for_time(t, strictly_greater)

    def read_rows(self, start_row_id: int, max_rows: int):
        """Tier-merged mirror of the backend ``read`` ABI: (planes,
        first_row_id, rows). Ordering is the demotion-race guard: the
        ring is read FIRST, then the gap below the ring's answer is
        filled from cold. Demotion encodes into cold BEFORE dropping
        from the ring, so any row the ring no longer has is either in
        the cold store or truly evicted — never in flight."""
        be = self._backend
        h_planes, h_first, h_n = be.read(start_row_id, max_rows)
        if self._tier is None or h_first <= start_row_id:
            return h_planes, h_first, h_n
        want = min(h_first - start_row_id, max_rows)
        c_planes, c_first, c_n = self._tier.store.read(start_row_id, want)
        if c_n == 0:
            return h_planes, h_first, h_n
        if c_first + c_n == h_first and h_n > 0 and c_n < max_rows:
            take_h = min(h_n, max_rows - c_n)
            planes = [
                np.concatenate([cp, hp[:take_h]])
                for cp, hp in zip(c_planes, h_planes)
            ]
            return planes, c_first, c_n + take_h
        return c_planes, c_first, c_n

    # -- read path -----------------------------------------------------------
    def cursor(
        self, start: StartSpec | None = None, stop: StopSpec | None = None
    ) -> Cursor:
        return Cursor(self, start or StartSpec(), stop or StopSpec())

    def scan(self, start_time=None, stop_time=None, window_rows: int = 1 << 17,
             prune=None):
        """Yield HostBatch windows, time-bounded (engine source interface).

        ``prune(row_lo, row_hi) -> bool`` (exec/zoneskip.py) is consulted
        per window BEFORE the read: True fast-forwards the cursor past
        [row_lo, row_hi) without touching either tier — for cold windows
        that means no decode at all.
        """
        if self._backend is None:
            return
        start = StartSpec.at_time(int(start_time)) if start_time is not None else StartSpec()
        stop = StopSpec.at_time(int(stop_time) - 1) if stop_time is not None else StopSpec()
        cur = self.cursor(start, stop)
        while not cur.done():
            if prune is not None:
                lo = cur._next_row_id
                hi = lo + window_rows
                if cur._stop_row_id is not None:
                    hi = min(hi, cur._stop_row_id)
                if hi > lo and prune(lo, hi):
                    cur.skip_to(hi)
                    continue
            hb = cur.next_batch(window_rows)
            if hb is None:
                break
            yield hb

    def stage_resident(self, window_rows: int | None = None) -> None:
        """Stage all complete windows onto the device (HBM cold store)."""
        from .device_cache import DeviceWindowCache, stage_window

        if self._backend is None:
            return
        w = int(window_rows or self.device_window_rows)
        if self._device_cache is None:
            self._device_cache = DeviceWindowCache()
        # Evict by the tier-merged first LIVE row: demoted-but-live rows
        # keep their staged device windows (repeat scans stay resident
        # and never re-decode), only true expiry reclaims them.
        self._device_cache.evict_before(self.first_row_id())
        end = self.end_row_id()
        self._staged_through = max(
            self._staged_through, (self.first_row_id() // w) * w
        )
        while self._staged_through + w <= end:
            k = self._staged_through // w
            first = max(k * w, self.first_row_id())
            n = min((k + 1) * w, end) - first
            if n > 0 and self._device_cache.get((w, k, first, n)) is None:
                win = stage_window(self, k, w)
                if win is not None:
                    self._device_cache.put((w, k, win.row0, win.n), win)
            self._staged_through = (k + 1) * w

    def device_scan(self, start_time=None, stop_time=None,
                    window_rows: int | None = None, start_row=None,
                    stop_row=None, prune=None):
        """Yield (DeviceWindow, lo_row, hi_row) covering the time range.

        Windows come from the device-resident cache when staged (zero
        transfer); misses — typically the partial tail window — stage on
        demand and are cached keyed by their length, so a grown tail
        re-stages while full windows stay immutable. ``start_row`` /
        ``stop_row`` clamp by absolute row id — the streaming
        (live-query) cursor's watermark interface. ``prune(lo, hi)``
        (exec/zoneskip.py) runs BEFORE the cache probe/stage, so a
        skipped window is never decoded or transferred.
        """
        from .device_cache import DeviceWindowCache, stage_window

        if self._backend is None:
            return
        w = int(window_rows or self.device_window_rows)
        if self._device_cache is None:
            self._device_cache = DeviceWindowCache()
        self._device_cache.evict_before(self.first_row_id())
        if w != self.device_window_rows:
            # Adopt the consumer's window size: future appends stage at w
            # (last consumer wins; differently-sized stagings are dead
            # weight for this consumer and are reclaimed now).
            self.device_window_rows = w
            self._staged_through = 0
        self._device_cache.evict_other_window_sizes(w)
        if start_time is not None:
            row0 = self.row_id_for_time(int(start_time), False)
        else:
            row0 = self.first_row_id()
        if start_row is not None:
            row0 = max(row0, int(start_row))
        start_row = row0
        if stop_time is not None:
            row1 = min(
                self.row_id_for_time(int(stop_time) - 1, True),
                self.end_row_id(),
            )
        else:
            row1 = self.end_row_id()
        if stop_row is not None:
            row1 = min(row1, int(stop_row))
        stop_row = row1
        if stop_row <= start_row:
            return
        for k in range(start_row // w, (stop_row + w - 1) // w):
            first = max(k * w, self.first_row_id())
            n = min((k + 1) * w, self.end_row_id()) - first
            if n <= 0:
                continue
            if prune is not None:
                plo = max(start_row, first)
                phi = min(stop_row, first + n)
                if phi > plo and prune(plo, phi):
                    continue
            win = self._device_cache.get((w, k, first, n))
            if win is None:
                win = stage_window(self, k, w)
                if win is None:
                    continue
                self._device_cache.put((w, k, win.row0, win.n), win)
            lo, hi = max(start_row, win.row0), min(stop_row, win.row0 + win.n)
            if hi > lo:
                yield win, lo, hi

    def _batch_from_planes(self, planes, cols=None) -> HostBatch:
        by_key = {k: p for k, p in zip(self._plane_layout, planes)}
        names = list(cols) if cols is not None else self.relation.column_names
        rel = self.relation.select(names)
        out_cols = {
            c: tuple(by_key[(c, i)] for i in range(len(host_dtypes(rel.col_type(c)))))
            for c in names
        }
        n = len(planes[0]) if planes else 0
        return HostBatch(
            relation=rel,
            cols=out_cols,
            length=n,
            dicts={c: d for c, d in self.dicts.items() if c in set(names)},
        )

    def read_all(self) -> HostBatch:
        """Materialize the whole table as one HostBatch (test/debug path)."""
        if self._backend is None:
            from ..exec.engine import _empty_host_batch

            return _empty_host_batch(self.relation, self.dicts)
        n = max(1, self.num_rows)
        planes, _, got = self.read_rows(self.first_row_id(), n)
        return self._batch_from_planes([p[:got] for p in planes])

    # -- introspection -------------------------------------------------------
    @property
    def num_rows(self) -> int:
        return self.stats().num_rows if self._backend is not None else 0

    @property
    def watermark_ns(self):
        """Max event-time ns ever appended (None without a time index).
        Monotonic by construction — ring expiry never regresses it."""
        st = self.col_stats.get(TIME_COLUMN)
        return st[1] if st is not None else None

    def stats(self) -> TableStats:
        """Snapshot of the backend counters + the freshness surface.
        The backend half is one locked stats() read; the row-id counters
        are two more locked reads (row ids are never reused, so
        end_row_id == rows ever appended and first_row_id == rows
        expired) — under concurrent appends the trio can straddle a
        batch, so exact cross-field reconciliation holds at quiesce."""
        if self._backend is None:
            return TableStats(0, 0, 0, 0, 0, 0, 0, 0, -1, 0)
        be = self._backend
        st = TableStats(*be.stats())
        st.rows_added = be.end_row_id()
        if self._tier is not None:
            # Tiered view: the whole ring is the hot tier, the encoded
            # store is the cold tier. Only cold EVICTION is expiry —
            # demotion moved rows, it didn't lose them — so the expiry
            # counters come from the cold store's eviction ledger (at
            # raw row widths, matching the ring's accounting).
            cs = self._tier.store
            st.hot_bytes = st.bytes
            st.cold_bytes = cs.nbytes
            st.bytes = st.hot_bytes + cs.nbytes
            st.hot_rows = st.num_rows
            st.cold_rows = cs.num_rows()
            st.num_rows = be.end_row_id() - self.first_row_id()
            st.num_batches += len(cs.windows)
            cold_min_t = cs.min_time()
            if cold_min_t is not None:
                st.min_time = cold_min_t
            st.rows_expired = cs.rows_evicted
            st.bytes_expired = cs.bytes_evicted_raw
            st.cold_raw_bytes = cs.raw_nbytes
            st.cold_windows = len(cs.windows)
            st.demotions = cs.demotions
            st.evictions = cs.evictions
            st.decode_seconds = cs.decode_seconds
        else:
            st.hot_rows = st.num_rows
            st.rows_expired = be.first_row_id()
            st.bytes_expired = st.bytes_added - st.bytes
        wm = self.watermark_ns
        st.watermark = wm if wm is not None else -1
        st.last_append_unix_ns = self._last_append_unix_ns
        st.ingest_rows_per_s = self._current_ingest_rate()
        dc = self._device_cache
        st.device_bytes = dc.nbytes if dc is not None else 0
        return st

    def _current_ingest_rate(self) -> float:
        """The EWMA, decayed at READ time: the EWMA itself only moves on
        appends, so a STOPPED ingest would report its last healthy rate
        forever. Capping at last-batch-rows / silence-elapsed decays the
        reported rate toward 0 as the silence grows, while an actively
        appending table (elapsed <= its inter-append interval) reports
        the EWMA unchanged."""
        last = self._last_append_mono
        if last is None:
            return 0.0
        elapsed = time.monotonic() - last
        if elapsed <= 0:
            return self._ingest_ewma
        return min(self._ingest_ewma, self._last_append_rows / elapsed)

    def freshness(self) -> dict:
        """Wire form of the freshness surface (agent heartbeat envelope
        + ``__tables__`` telemetry fold): live sizes, monotonic append/
        expiry counters, the event-time watermark pair, wall time of the
        last append and the ingest-rate EWMA."""
        st = self.stats()
        return {
            "rows": st.num_rows,
            "bytes": st.bytes,
            "hot_bytes": st.hot_bytes,
            "cold_bytes": st.cold_bytes,
            "device_bytes": st.device_bytes,
            "rows_total": st.rows_added,
            "bytes_total": st.bytes_added,
            "expired_rows_total": st.rows_expired,
            "expired_bytes_total": st.bytes_expired,
            "watermark": st.watermark,
            "min_time": st.min_time,
            "last_append": st.last_append_unix_ns,
            "ingest_rows_per_s": round(st.ingest_rows_per_s, 3),
            # storage-tier split (zeros for untiered tables)
            "hot_rows": st.hot_rows,
            "cold_rows": st.cold_rows,
            "cold_raw_bytes": st.cold_raw_bytes,
            "cold_demotions_total": st.demotions,
            "cold_evictions_total": st.evictions,
            "cold_decode_seconds_total": round(st.decode_seconds, 6),
        }


def max_watermark_ns(tablets):
    """Max event-time watermark across ``tablets`` (None = no time
    index / nothing appended anywhere). THE freshness sweep: the
    engine's per-scan staleness stamp, the streaming cursor's per-poll
    note and the result cache's validity reads all go through this one
    helper — one sweep per poll/scan round, never one per consumer
    (the same dedup PR 14 applied to the heartbeat path). Callers
    resolve it through the module (``table.max_watermark_ns``) so the
    regression test can count sweeps."""
    wm = -1
    for t in tablets:
        w = getattr(t, "watermark_ns", None)
        if w is not None and w > wm:
            wm = int(w)
    return None if wm < 0 else wm
