"""Device-resident table windows: HBM as the cold store.

Reference contrast: Carnot's Table keeps hot ColumnWrapper batches and
cold Arrow slabs in host RAM (``src/table_store/table/table.h:104``), and
every query re-reads them. On TPU the equivalent of "cold" is **HBM**:
a full window of rows is staged onto the device once — at append time,
asynchronously — and every subsequent query consumes the already-resident
buffers, so steady-state queries perform zero host->device transfers of
table data (SURVEY.md §7 stage 1, §5 long-context).

Windows are aligned to absolute row-id multiples of ``window_rows`` (row
ids are monotone and never reused — ``table.h`` unique-row-id cursors), so
a window's content is immutable once full. Partial tail windows are cached
keyed by their current length and re-staged as they grow; expired windows
are evicted. An LRU byte budget (``device_cache_bytes``) bounds HBM use.
"""

from __future__ import annotations

import itertools
import threading
import weakref
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from ..config import get_flag
from ..types.dtypes import device_dtypes, pad_values

# Global LRU accounting: the device_cache_bytes budget bounds the SUM of
# resident windows across every table's cache (one HBM, many tables), so
# eviction picks the globally least-recently-used window. The registry
# is process-global while engines are per-agent: one agent's staging
# loop iterates it while another agent's table creation add()s, so
# every traversal goes through a locked snapshot ("Set changed size
# during iteration" otherwise — observed as a cluster-test flake).
_CACHES: "weakref.WeakSet[DeviceWindowCache]" = weakref.WeakSet()
_CACHES_LOCK = threading.Lock()
_TICK = itertools.count()


def _caches() -> list:
    with _CACHES_LOCK:
        return list(_CACHES)


def total_resident_bytes() -> int:
    return sum(c._bytes for c in _caches())


def _enforce_global_budget(newest: tuple) -> None:
    """Evict globally-LRU windows until under budget; the just-inserted
    window (``newest`` = (cache, key)) always survives."""
    budget = get_flag("device_cache_bytes")
    while total_resident_bytes() > budget:
        victim = None  # (tick, cache, key)
        for c in _caches():
            # Snapshot: another engine's concurrent get()/put() moves
            # its own cache's ticks. Eviction choice is best-effort
            # under that race; the traversal must not crash.
            for k, t in list(c._ticks.items()):
                if (c, k) == newest:
                    continue
                if victim is None or t < victim[0]:
                    victim = (t, c, k)
        if victim is None:
            break
        victim[1]._evict(victim[2])


@dataclass
class DeviceWindow:
    """One staged window: device column planes + occupancy info.

    ``cols`` maps column name -> tuple of jnp planes, each of length
    ``capacity`` (== the window size, a power of two). Rows
    [row0, row0 + n) are live; the validity mask for a query's row range
    is computed on device by the engine (cheap iota compares).
    """

    row0: int  # absolute row id of slot 0
    n: int  # live rows staged
    capacity: int
    cols: dict  # {name: tuple(jnp arrays)}
    nbytes: int


class DeviceWindowCache:
    """Cache of staged windows for one Table; budget enforced globally."""

    def __init__(self):
        self._entries: OrderedDict[tuple, DeviceWindow] = OrderedDict()
        self._ticks: dict[tuple, int] = {}
        self._bytes = 0
        with _CACHES_LOCK:
            _CACHES.add(self)

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def nbytes(self) -> int:
        return self._bytes

    def get(self, key: tuple) -> DeviceWindow | None:
        win = self._entries.get(key)
        if win is not None:
            self._entries.move_to_end(key)
            self._ticks[key] = next(_TICK)
        return win

    def put(self, key: tuple, win: DeviceWindow) -> None:
        old = self._entries.pop(key, None)
        if old is not None:
            self._bytes -= old.nbytes
        self._entries[key] = win
        self._ticks[key] = next(_TICK)
        self._bytes += win.nbytes
        # Evict partial-window predecessors of the same (window_rows,
        # window_index) — key = (W, k, row0, n): a grown window supersedes
        # its stale shorter stagings.
        stale = [
            k for k in self._entries if k[:2] == key[:2] and k != key
        ]
        for k in stale:
            self._evict(k)
        _enforce_global_budget(newest=(self, key))

    def _evict(self, key: tuple) -> None:
        win = self._entries.pop(key, None)
        self._ticks.pop(key, None)
        if win is not None:
            self._bytes -= win.nbytes

    def evict_other_window_sizes(self, window_rows: int) -> None:
        """Drop entries staged at a different window size.

        A consumer scanning at W can never hit a (W', ...) entry; leaving
        them resident would double HBM use when append-time staging
        (keyed by the ``window_rows`` flag) disagrees with an engine's
        explicit ``window_rows`` override.
        """
        stale = [k for k in self._entries if k[0] != window_rows]
        for k in stale:
            self._evict(k)

    def evict_before(self, first_row_id: int) -> None:
        """Drop windows fully expired from the table."""
        stale = [
            k
            for k, w in self._entries.items()
            if w.row0 + w.n <= first_row_id
        ]
        for k in stale:
            self._evict(k)

    def clear(self) -> None:
        self._entries.clear()
        self._ticks.clear()
        self._bytes = 0


def stage_window(table, window_index: int, window_rows: int) -> DeviceWindow | None:
    """Read window ``window_index`` (rows [k*W, (k+1)*W)) and place it on
    device. Returns None for an empty window. The device_put is
    asynchronous — callers at append time pay only the host read/pad."""
    import jax.numpy as jnp

    from ..types.batch import bucket_capacity

    # Tier-merged read (Table.read_rows): a window straddling the
    # demotion boundary assembles from decoded cold rows + hot ring rows
    # transparently — the decode runs on THIS thread, which under the
    # WindowPipeline is the prefetch producer (decode-on-stage overlap).
    lo = window_index * window_rows
    planes, first, n = table.read_rows(
        max(lo, table.first_row_id()), window_rows
    )
    hi_cap = (window_index + 1) * window_rows
    if n > 0 and first + n > hi_cap:  # clip reads that ran past the window
        n = max(0, hi_cap - first)
        planes = [p[:n] for p in planes]
    if n <= 0:
        return None
    cap = bucket_capacity(window_rows)
    mult = getattr(table, "stage_capacity_multiple", 1)
    if mult > 1:
        from ..parallel.mesh import pad_to_multiple

        cap = pad_to_multiple(cap, mult)
    sharding = getattr(table, "stage_sharding", None)
    cols: dict = {}
    nbytes = 0
    for (cname, plane_i), p in zip(table._plane_layout, planes):
        dt = table.relation.col_type(cname)
        ddt = np.dtype(device_dtypes(dt)[plane_i])  # f64 -> f32 etc.
        padded = np.full(cap, pad_values(dt)[plane_i], dtype=ddt)
        padded[:n] = p
        if sharding is not None:
            # Mesh residency: the window lives row-sharded across the
            # engine's mesh — each virtual PEM holds its shard in HBM.
            import jax

            arr = jax.device_put(padded, sharding)
        else:
            arr = jnp.asarray(padded)
        cols.setdefault(cname, {})[plane_i] = arr
        nbytes += cap * ddt.itemsize
    cols = {
        c: tuple(v[i] for i in sorted(v)) for c, v in cols.items()
    }
    return DeviceWindow(
        row0=first, n=n, capacity=cap, cols=cols, nbytes=nbytes
    )
