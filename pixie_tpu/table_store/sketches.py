"""Per-tablet ingest sketches: row count, zone maps, HLL NDV.

Maintained incrementally on the table-store push path (``Table.append``)
so plan-time decisions never scan data:

- **row count** — exact rows ever appended (expiry never decrements:
  all uses are conservative upper bounds).
- **zone maps** — per key column, the global (min, max) plus a bounded
  ring of per-append-batch (first_row_id, n, min, max) entries. The
  global bounds feed join routing today (capacity-estimate overlap,
  host-path range pre-filters; the windowed join driver computes its
  per-window bounds exactly from the packed probe keys, which is both
  cheaper and tighter than row-id zone lookups post-filtering). The
  per-batch ring (``window_zone``) is the seam for predicate-driven
  scan-window skipping — the ROADMAP "skip staging windows whose zone
  maps can't match the predicate" item — where the scan DOES address
  windows by row id.
- **HLL NDV** — one ``ops/hll.py`` register row per key column (the
  numpy mirror: bit-identical registers to the device kernel, no jax
  dispatch on the append path). NDV × rows picks the join build side;
  rows / NDV is the join-cardinality estimate that sizes output
  capacity up front instead of climbing the overflow-doubling ladder.

Sketched columns are the single-plane integer/time columns (the same
set ``Table.col_stats`` bounds) plus dictionary-encoded string code
columns. Multi-plane columns (UINT128) and floats are not sketched —
joins on those route through the exact densify path where no cheap
zone arithmetic applies.

Reference grounding: PAPERS.md "Online Sketch-based Query Optimization"
(2102.02440) — sketches maintained online, consulted at plan time; the
reference engine has no analog (Carnot's planner is stats-blind).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..ops.hll import DEFAULT_P, hll_estimate_np, hll_init_np, hll_update_np

#: Per-append zone-map entries kept per column; beyond this the oldest
#: entries merge pairwise (coverage stays total, granularity halves) so
#: long-lived streaming tables can't grow the ring unboundedly.
MAX_ZONE_ENTRIES = 1024


@dataclass
class ZoneEntry:
    row0: int  # first row id of the appended batch
    n: int  # rows in the batch
    lo: int
    hi: int


@dataclass
class ColumnSketch:
    """Ingest sketch for one key column."""

    rows: int = 0
    lo: int | None = None  # global zone map (min over all appends)
    hi: int | None = None
    registers: np.ndarray = field(default_factory=hll_init_np)
    zones: list = field(default_factory=list)  # list[ZoneEntry]

    def update(self, values: np.ndarray, row0: int) -> None:
        """Fold one appended batch (single int plane / string codes)."""
        n = len(values)
        if n == 0:
            return
        lo, hi = int(values.min()), int(values.max())
        self.rows += n
        self.lo = lo if self.lo is None else min(self.lo, lo)
        self.hi = hi if self.hi is None else max(self.hi, hi)
        hll_update_np(self.registers, values, DEFAULT_P)
        self.zones.append(ZoneEntry(row0, n, lo, hi))
        if len(self.zones) > MAX_ZONE_ENTRIES:
            merged = []
            it = iter(self.zones)
            for a in it:
                b = next(it, None)
                if b is None:
                    merged.append(a)
                elif b.row0 == a.row0 + a.n:
                    merged.append(ZoneEntry(
                        a.row0, a.n + b.n, min(a.lo, b.lo), max(a.hi, b.hi)
                    ))
                else:  # non-contiguous (expiry gap): keep both
                    merged.extend((a, b))
            self.zones = merged

    @property
    def ndv(self) -> int:
        """Estimated distinct values (HLL, ~3% error), capped by rows."""
        return max(1, min(hll_estimate_np(self.registers), self.rows))

    def window_zone(self, row_lo: int, row_hi: int):
        """Conservative (min, max) over rows [row_lo, row_hi), or None
        when no zone entry overlaps (e.g. the range pre-dates sketching
        or lies in an expiry gap — callers must treat None as
        unbounded)."""
        lo = hi = None
        for z in self.zones:
            if z.row0 + z.n <= row_lo or z.row0 >= row_hi:
                continue
            lo = z.lo if lo is None else min(lo, z.lo)
            hi = z.hi if hi is None else max(hi, z.hi)
        if lo is None:
            return None
        return lo, hi


class TableSketches:
    """All of one tablet's column sketches + the exact row count."""

    def __init__(self):
        self.rows = 0
        self.cols: dict[str, ColumnSketch] = {}

    def update(self, name: str, values: np.ndarray, row0: int) -> None:
        self.cols.setdefault(name, ColumnSketch()).update(values, row0)

    def col(self, name: str) -> ColumnSketch | None:
        return self.cols.get(name)

    def ndv(self, name: str) -> int | None:
        s = self.cols.get(name)
        return s.ndv if s is not None and s.rows else None
