"""Hot/cold columnar table store (reference: ``src/table_store``)."""

from .table import Cursor, StartSpec, StopSpec, Table, TableStats
from .table_store import TableStore

__all__ = [
    "Cursor",
    "StartSpec",
    "StopSpec",
    "Table",
    "TableStats",
    "TableStore",
]
