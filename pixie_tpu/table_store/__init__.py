"""Hot/cold columnar table store (reference: ``src/table_store``)."""

from .sketches import ColumnSketch, TableSketches
from .table import Cursor, StartSpec, StopSpec, Table, TableStats
from .table_store import TableStore

__all__ = [
    "ColumnSketch",
    "Cursor",
    "StartSpec",
    "StopSpec",
    "Table",
    "TableSketches",
    "TableStats",
    "TableStore",
]
