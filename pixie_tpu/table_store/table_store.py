"""TableStore: the name/id -> Table map shared by ingest and queries.

Reference parity: ``src/table_store/table/table_store.h:79`` — tables are
addressable by name and by numeric id (ingest pushes by id), with tablet
support (``tablets_group.h``): a (table, tablet_id) pair maps to its own
physical Table, and reads over the table see all tablets.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..types.relation import Relation
from .table import DEFAULT_COMPACTED_ROWS, Table

DEFAULT_TABLET = ""


class TableStore:
    def __init__(self):
        self._lock = threading.Lock()
        # name -> {tablet_id -> Table}
        self._tables: dict[str, dict[str, Table]] = {}
        self._ids: dict[int, str] = {}
        self._names_to_ids: dict[str, int] = {}
        self._next_id = 1
        # Lazy per-table byte budgets: {name: max_bytes, "*": default}.
        # Applied when a table is created with no explicit max_bytes —
        # the PEM installs the pem_manager.cc InitSchemas split here so
        # ingest is bounded without pre-pinning schemas.
        self.table_budgets: dict = {}

    def _budget_for(self, name: str, max_bytes: int) -> int:
        if max_bytes != -1 or not self.table_budgets:
            return max_bytes
        return self.table_budgets.get(name, self.table_budgets.get("*", -1))

    def add_table(
        self,
        name: str,
        relation: Relation | None = None,
        table_id: Optional[int] = None,
        max_bytes: int = -1,
        compacted_rows: int = DEFAULT_COMPACTED_ROWS,
        tablet_id: str = DEFAULT_TABLET,
    ) -> Table:
        with self._lock:
            base = next(iter(self._tables.get(name, {}).values()), None)
            t = Table(
                name,
                relation,
                max_bytes=self._budget_for(name, max_bytes),
                compacted_rows=compacted_rows,
                dicts=base.dicts if base is not None else None,
            )
            self._tables.setdefault(name, {})[tablet_id] = t
            if name not in self._names_to_ids:
                tid = table_id if table_id is not None else self._next_id
                self._next_id = max(self._next_id, tid) + 1
                self._ids[tid] = name
                self._names_to_ids[name] = tid
            return t

    def ensure_table(
        self,
        name: str,
        relation: Relation | None = None,
        max_bytes: int = -1,
        device_window_rows: int | None = None,
    ) -> Table:
        """Atomic get-or-create of a table's default tablet (check-then-act
        callers racing on first append must not replace each other)."""
        with self._lock:
            existing = next(iter(self._tables.get(name, {}).values()), None)
            if existing is not None:
                return existing
            t = Table(name, relation, max_bytes=self._budget_for(name, max_bytes))
            if device_window_rows is not None:
                t.device_window_rows = device_window_rows
            self._tables.setdefault(name, {})[DEFAULT_TABLET] = t
            if name not in self._names_to_ids:
                self._ids[self._next_id] = name
                self._names_to_ids[name] = self._next_id
                self._next_id += 1
            return t

    def get_table(self, name_or_id, tablet_id: str = DEFAULT_TABLET) -> Optional[Table]:
        with self._lock:
            name = (
                self._ids.get(name_or_id) if isinstance(name_or_id, int) else name_or_id
            )
            if name is None:
                return None
            return self._tables.get(name, {}).get(tablet_id)

    def get_table_id(self, name: str) -> Optional[int]:
        with self._lock:
            return self._names_to_ids.get(name)

    def get_table_name(self, table_id: int) -> str:
        with self._lock:
            return self._ids.get(table_id, "")

    def table_ids(self) -> list[int]:
        with self._lock:
            return sorted(self._ids)

    def table_names(self) -> list[str]:
        with self._lock:
            return sorted(self._tables)

    def tablets(self, name: str) -> list[Table]:
        with self._lock:
            return [t for _, t in sorted(self._tables.get(name, {}).items())]

    def append_data(
        self, name_or_id, data, tablet_id: str = DEFAULT_TABLET, time_cols=("time_",)
    ):
        """Ingest push target (table_store.h:152 AppendData). Creates the
        tablet on first write; the table itself must already exist when
        addressed by id."""
        t = self.get_table(name_or_id, tablet_id)
        if t is None:
            with self._lock:
                name = (
                    self._ids.get(name_or_id)
                    if isinstance(name_or_id, int)
                    else name_or_id
                )
                if name is None:
                    raise KeyError(f"no table with id {name_or_id}")
                tablets = self._tables.setdefault(name, {})
                if tablet_id not in tablets:
                    # New tablets inherit the base tablet's schema, byte
                    # budget, and (shared) string dictionaries so every
                    # tablet encodes into one id space.
                    base = next(iter(tablets.values()), None)
                    t_new = Table(
                        name,
                        base.relation if base is not None else None,
                        max_bytes=base.max_bytes if base is not None else -1,
                        compacted_rows=(
                            base.compacted_rows
                            if base is not None
                            else DEFAULT_COMPACTED_ROWS
                        ),
                        dicts=base.dicts if base is not None else None,
                    )
                    if base is not None:
                        t_new.device_window_rows = base.device_window_rows
                    tablets[tablet_id] = t_new
                if name not in self._names_to_ids:
                    self._names_to_ids[name] = self._next_id
                    self._ids[self._next_id] = name
                    self._next_id += 1
                t = tablets[tablet_id]
        return t.append(data, time_cols=time_cols)

    def compact_all(self) -> int:
        """One compaction pass over every tablet (the background
        compaction-thread body; reference runs this off a timer)."""
        n = 0
        for tablets in list(self._tables.values()):
            for t in list(tablets.values()):
                n += t.compact()
        return n

    def relation(self, name: str) -> Optional[Relation]:
        tablets = self._tables.get(name)
        if not tablets:
            return None
        return next(iter(tablets.values())).relation

    def freshness(self) -> dict:
        """{table: merged freshness dict} across each table's tablets —
        the per-AGENT half of the cluster merge the tracker performs:
        monotonic counters and live sizes sum, watermarks/last-append
        take the max, min_time the min (tablets of one logical table
        are disjoint row shards)."""
        out: dict = {}
        for name in self.table_names():
            merged = None
            for t in self.tablets(name):
                if t._backend is None:
                    continue
                merged = merge_freshness(merged, t.freshness())
            if merged is not None:
                out[name] = merged
        return out


#: Freshness keys that merge by summation (live sizes + monotonic
#: counters over disjoint shards); the rest are watermark-style.
_FRESHNESS_SUM_KEYS = (
    "rows", "bytes", "hot_bytes", "cold_bytes", "device_bytes",
    "rows_total", "bytes_total", "expired_rows_total",
    "expired_bytes_total", "ingest_rows_per_s",
    # storage-tier split (coldstore.py; zeros for untiered tablets)
    "hot_rows", "cold_rows", "cold_raw_bytes",
    "cold_demotions_total", "cold_evictions_total",
    "cold_decode_seconds_total",
)


def merge_freshness(into: dict | None, fresh: dict) -> dict:
    """Fold one tablet/agent freshness record into an accumulator
    (shared by TableStore.freshness and the tracker's cluster merge):
    sums for counters, max for ``watermark``/``last_append``, min for
    ``min_time`` (-1 = no live rows, ignored)."""
    if into is None:
        return dict(fresh)
    for k in _FRESHNESS_SUM_KEYS:
        into[k] = into.get(k, 0) + fresh.get(k, 0)
    for k in ("watermark", "last_append"):
        into[k] = max(into.get(k, -1), fresh.get(k, -1))
    mt_a, mt_b = into.get("min_time", -1), fresh.get("min_time", -1)
    into["min_time"] = (
        mt_b if mt_a < 0 else (mt_a if mt_b < 0 else min(mt_a, mt_b))
    )
    return into
