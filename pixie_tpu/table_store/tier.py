"""Tier manager: byte-budget demotion from the hot ring into the cold store.

Policy (docs/STORAGE.md): a tiered table keeps its backend ring
*unbounded* and the manager enforces the budgets instead —

- ``hot_budget_bytes`` (the table's ``max_bytes``): before every append,
  the oldest hot rows demote window-by-window into the encoded cold
  store until the incoming batch fits. Demotion is a **handoff, not
  expiry**: rows are encoded into ``ColdStore`` *first* and only then
  dropped from the ring (``drop_before``), so a concurrent reader always
  finds every live row in exactly one tier (readers consult the ring
  first, then fill the gap from cold — ``Table.read_rows``). None of the
  expiry counters move.
- ``cold_budget_bytes`` (``cold_tier_mb`` flag): after demotion, the
  oldest *encoded* windows evict until the encoded footprint fits. That
  is true expiry — ``rows_expired`` / ``bytes_expired`` advance (at raw
  row widths, matching the hot ring's accounting).

Demotion chunks align to the table's device window grid so previously
staged device windows keep their (window, row0, n) identity across
demotion and repeat scans stay device-resident.
"""

from __future__ import annotations

import threading

import numpy as np

from .coldstore import ColdStore

MB = 1 << 20


class TierManager:
    def __init__(self, table, hot_budget_bytes: int, cold_budget_bytes: int):
        self.table = table
        self.hot_budget = int(hot_budget_bytes)
        self.cold_budget = int(cold_budget_bytes)
        has_time = bool(
            table._plane_layout and table._plane_layout[0][0] == "time_"
            and table._plane_layout[0][1] == 0
        )
        self.store = ColdStore(has_time)
        self._monotonic = [
            i == 0 and has_time for i in range(len(table._plane_layout))
        ]
        self.lock = threading.Lock()

    @property
    def row_bytes(self) -> int:
        be = self.table._backend
        return int(sum(np.dtype(d).itemsize for d in be.elem_dtypes))

    def demote_for(self, incoming_bytes: int) -> int:
        """Demote oldest hot rows so the ring fits incoming_bytes more.
        Called on the append path BEFORE the backend append. Returns rows
        demoted."""
        be = self.table._backend
        hot_bytes = be.stats()[0]
        need = hot_bytes + int(incoming_bytes) - self.hot_budget
        if need <= 0:
            return 0
        rb = self.row_bytes
        rows = -(-need // rb) if rb > 0 else 0
        return self.demote_rows(rows)

    def demote_rows(self, rows: int) -> int:
        """Demote at least ``rows`` oldest hot rows (rounded up to the
        device window grid), encode them, then drop them from the ring."""
        if rows <= 0:
            return 0
        be = self.table._backend
        w = max(1, int(self.table.device_window_rows))
        demoted = 0
        with self.lock:
            while demoted < rows:
                first = be.first_row_id()
                end = be.end_row_id()
                if first >= end:
                    break
                chunk_end = min((first // w + 1) * w, end)
                planes, got_first, n = be.read(first, chunk_end - first)
                if n <= 0:
                    break
                if self.store.has_time:
                    times = planes[0]
                    mn, mx = int(times.min()), int(times.max())
                else:
                    mn, mx = 0, 0
                self.store.append_window(
                    got_first, planes, mn, mx, self._monotonic
                )
                be.drop_before(got_first + n)
                demoted += n
            self.store.evict_to(self.cold_budget)
        return demoted

    def counters(self) -> dict:
        s = self.store
        return {
            "cold_windows": len(s.windows),
            "cold_bytes": s.nbytes,
            "cold_raw_bytes": s.raw_nbytes,
            "cold_rows": s.num_rows(),
            "demotions_total": s.demotions,
            "evictions_total": s.evictions,
            "rows_evicted_total": s.rows_evicted,
            "decode_windows_total": s.decoded_windows,
            "decode_bytes_total": s.decoded_bytes,
            "decode_seconds_total": round(s.decode_seconds, 6),
        }
