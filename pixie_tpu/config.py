"""Uniform config/flag system with environment-variable fallback.

Reference parity: the gflags + ``StringFromEnv`` idiom used throughout the
reference (``src/carnot/carnot_executable.cc:40-50``,
``src/vizier/services/agent/pem/pem_manager.cc:26-33``) and the Go
pflag/viper layer (``src/shared/services/service_flags.go``). One registry:
every tunable declares a name, type, default and doc here; the value
resolves from (in order) an explicit ``set_flag`` override, the
``PIXIE_TPU_<NAME>`` environment variable, then the default.
"""

from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass
from typing import Callable


@dataclass
class Flag:
    name: str
    default: object
    parse: Callable
    doc: str
    # Computed once at definition: flag reads sit on per-compile hot
    # paths (verify/bounds memo keys), where rebuilding the env-var
    # string per get_flag call measurably added up.
    env_var: str = ""

    def __post_init__(self):
        if not self.env_var:
            self.env_var = "PIXIE_TPU_" + self.name.upper()


_REGISTRY: dict[str, Flag] = {}
_OVERRIDES: dict[str, object] = {}
_LOCK = threading.Lock()


def _parse_bool(s) -> bool:
    if isinstance(s, bool):
        return s
    return str(s).strip().lower() in ("1", "true", "yes", "on")


def define_flag(name: str, default, doc: str, parse: Callable | None = None) -> None:
    if parse is None:
        if isinstance(default, bool):
            parse = _parse_bool
        elif isinstance(default, int):
            parse = int
        elif isinstance(default, float):
            parse = float
        else:
            parse = str
    with _LOCK:
        _REGISTRY[name] = Flag(name=name, default=default, parse=parse, doc=doc)


def get_flag(name: str):
    f = _REGISTRY[name]
    with _LOCK:
        if name in _OVERRIDES:
            return _OVERRIDES[name]
    env = os.environ.get(f.env_var)
    if env is not None:
        return f.parse(env)
    return f.default


_MISSING = object()


def get_flags(*names) -> tuple:
    """Batch ``get_flag``: one lock acquisition for N flags. For hot
    paths that snapshot several flags per call (the analysis passes'
    memo keys read five per compile)."""
    flags = [_REGISTRY[n] for n in names]
    environ = os.environ
    with _LOCK:
        ov = [_OVERRIDES.get(n, _MISSING) for n in names]
    out = []
    for f, o in zip(flags, ov):
        if o is not _MISSING:
            out.append(o)
            continue
        env = environ.get(f.env_var)
        out.append(f.parse(env) if env is not None else f.default)
    return tuple(out)


def set_flag(name: str, value) -> None:
    """Programmatic override (the runtime ConfigUpdateMessage analog)."""
    f = _REGISTRY[name]
    with _LOCK:
        _OVERRIDES[name] = f.parse(value) if not isinstance(value, type(f.default)) else value


def clear_flag(name: str) -> None:
    with _LOCK:
        _OVERRIDES.pop(name, None)


@contextlib.contextmanager
def override_flag(name: str, value):
    """Scoped ``set_flag`` that restores any PRE-EXISTING programmatic
    override on exit (a bare set/clear pair would delete a caller's own
    override, silently flipping later runs back to the default)."""
    with _LOCK:
        had = name in _OVERRIDES
        prev = _OVERRIDES.get(name)
    set_flag(name, value)
    try:
        yield
    finally:
        if had:
            set_flag(name, prev)
        else:
            clear_flag(name)


def all_flags() -> dict:
    """{name: (value, doc)} snapshot — the --helpfull / statusz listing."""
    return {n: (get_flag(n), f.doc) for n, f in sorted(_REGISTRY.items())}


# -- engine/table tunables ---------------------------------------------------
define_flag("window_rows", 1 << 17,
            "Rows per streamed device window (engine + device residency).")
define_flag("max_groups", 4096,
            "Initial group-by capacity; overflow doubles it and re-runs.")
define_flag("max_groups_limit", 1 << 22,
            "Hard cap for group-by rebucketing growth.")
define_flag("groupby_impl", "auto",
            "Per-window group-id algorithm for keys WITHOUT a static dense "
            "domain: 'auto' picks per backend (sort on TPU, hash on CPU), "
            "'sort' forces the multi-key stable sort (data-independent "
            "runtime; XLA TPU sorts are fast), 'hash' forces the bounded-"
            "probe device table (scatter-heavy; fast on CPU, poor on the "
            "tunnel's synchronous dispatch mode).")
define_flag("dense_domain_limit", 1 << 20,
            "Group-bys whose key columns all have statically-known domains "
            "(dictionary-encoded strings, booleans) with product <= this "
            "use the packed key AS the group id: no sort, no hash, and "
            "slot-aligned (regroup-free) state merges.")
define_flag("int_dense_domain_limit", 1 << 23,
            "Dense-domain budget for group-bys whose keys include integer "
            "columns bounded by table min/max stats (Table.col_stats). "
            "Separate from dense_domain_limit because a single int key "
            "can't suffer the multi-key packing blowup; the agg carry is "
            "one slot per domain value.")
define_flag("fold_scan_windows", 16,
            "Fold up to this many equal-shape device-resident windows per "
            "aggregate dispatch via one lax.scan program (1 disables); "
            "each dispatch costs a tunnel round trip in the synchronous "
            "regime, so batching windows amortizes it.")
define_flag("pipeline_depth", 2,
            "Window-executor prefetch depth: host slicing/packing/"
            "device_put of window N+1 runs on a background thread while "
            "window N computes, with at most this many windows in "
            "flight. 1 = serial (no prefetch thread, today's behavior).")
define_flag("join_probe_window_rows", 1 << 20,
            "Probe rows per device-join dispatch for inner/left N:M "
            "joins: the build side is sorted and staged on device ONCE "
            "per query and probe windows stream through the prefetch "
            "pipeline. 0 = single-shot kernel over the whole probe side.")
define_flag("ingest_sketches", True,
            "Maintain per-tablet ingest sketches (row count, HLL NDV, "
            "zone maps on key columns) on the append path; join routing "
            "and the planner's eager-aggregation sizing consult them.")
define_flag("join_strategy", "auto",
            "N:M join strategy: 'auto' (sketch-guided routing picks "
            "host-dict / host-hash / single-shot / windowed sorted-probe "
            "/ windowed radix by shape, backend and sketches), or force "
            "'host', 'single', 'sorted', 'radix' for testing/bench.")
define_flag("join_radix_bits", 8,
            "Radix bits for the partitioned device join: build keys are "
            "splitmix64-hashed and partitioned by the top bits, so each "
            "probe row binary-searches ONE partition instead of the "
            "whole build side. 0 disables the radix strategy entirely.")
define_flag("join_capacity_safety", 2.0,
            "Multiplier on the sketch-estimated join output cardinality "
            "when sizing the initial device-join output capacity (then "
            "rounded to a power-of-two bucket). Headroom over the "
            "NDV-based mean fan-out absorbs moderate key skew; an "
            "overflow retry costs a fresh jit compile mid-query, so "
            "over-sizing is the cheaper error.")
define_flag("join_zone_skip", True,
            "Skip staging probe windows whose key zone map cannot "
            "intersect the build side's key range (inner/left windowed "
            "device joins; left windows emit their null rows host-side).")
define_flag("device_residency", True,
            "Stage full table windows into device memory (HBM) at append "
            "time so steady-state queries run without host transfers.")
define_flag("device_cache_bytes", 6 << 30,
            "Byte budget for device-resident table windows (LRU-evicted).")
define_flag("device_join_min_rows", 1 << 15,
            "Combined row count above which joins route to the device kernel.")
define_flag("agent_heartbeat_s", 5.0, "Agent heartbeat period (seconds).")
define_flag("agent_expiry_s", 60.0, "Tracker agent expiry after silence.")
define_flag(
    "pallas_dense_fold", "auto",
    "Pallas MXU dense-fold kernel routing: 'auto' (TPU backend only), "
    "'interpret' (any backend, interpreter mode — tests), 'off'.",
)
define_flag(
    "pallas_tdigest", "auto",
    "Pallas t-digest histogram kernel routing: 'auto' (TPU backend, "
    "small slot counts), 'interpret' (tests), 'off'.",
)
define_flag(
    "cpu_fold_threads", 0,
    "CPU-backend parallel window fold: thread count (0 = auto from cores, "
    "1 = disable and fold sequentially).",
)
define_flag(
    "table_store_data_limit_mb", 1024 + 256,
    "Byte budget across ALL canonical ingest tables (reference "
    "PL_TABLE_STORE_DATA_LIMIT_MB, default 1.25GB); <= 0 = unbounded.",
)
define_flag(
    "table_store_http_events_percent", 40,
    "Percent of the table-store budget devoted to http_events "
    "(reference PL_TABLE_STORE_HTTP_EVENTS_PERCENT).",
)
define_flag(
    "cold_tier_mb", 0,
    "Encoded cold-tier byte budget per table (table_store/coldstore.py). "
    "> 0 enables tiering for byte-bounded tables: the oldest hot-ring "
    "windows demote into dictionary/delta/run-length encoded cold "
    "windows instead of expiring, and only cold evictions count as "
    "expiry. 0 = cold tier off (hot ring expires directly, the "
    "pre-tier behavior).",
)
define_flag(
    "scan_zone_skip", True,
    "Skip scan windows whose per-column zone maps cannot satisfy a "
    "query's FilterOp predicate (exec/zoneskip.py) — checked BEFORE "
    "stage/decode, so selective scans over cold data never decode "
    "dead windows. Generalizes join_zone_skip to plain table scans.",
)
define_flag(
    "bus_secret", "",
    "Shared secret for netbus/broker bearer tokens; empty disables auth "
    "(single-trust-domain deployments).",
)

# -- fault tolerance (services/query_broker.py, tracker.py) ------------------
define_flag(
    "dispatch_retries", 3,
    "Re-publishes of an un-acked fragment dispatch before the broker "
    "declares the agent lost (0 = a single un-acked attempt is lost).",
)
define_flag(
    "dispatch_backoff_ms", 50.0,
    "Initial ack-wait/backoff for fragment dispatch retries; doubles "
    "per attempt (capped at 2s) with +0..25% jitter.",
)
define_flag(
    "require_complete", False,
    "Fail a distributed query as soon as a participating data agent is "
    "lost, instead of completing with partial results from the "
    "survivors (the pre-fault-tolerance fail-closed behavior).",
)
define_flag(
    "agent_flap_threshold", 3,
    "Expirations within agent_flap_window_s that quarantine an agent "
    "out of distributed query planning.",
)
define_flag(
    "agent_flap_window_s", 300.0,
    "Sliding window (seconds) for counting agent expirations toward "
    "the flap threshold.",
)
define_flag(
    "agent_quarantine_s", 120.0,
    "Cooldown during which a quarantined (flapping) agent is excluded "
    "from distributed_state() planning; it may re-register and "
    "heartbeat meanwhile.",
)

# -- broker HA (services/broker_ha.py; docs/RESILIENCE.md "Broker HA") -------
define_flag(
    "broker_lease_interval_s", 0.5,
    "Cadence of the leader's broker.lease heartbeat and of each "
    "standby's expiry check / presence announcement.",
)
define_flag(
    "broker_lease_expiry_s", 2.0,
    "Lease age past which a standby declares the leader dead and the "
    "lowest-id standby claims the next epoch (each higher-ranked "
    "standby waits one extra lease interval before claiming).",
)
define_flag(
    "broker_reconcile_wait_s", 0.5,
    "How long a freshly elected leader collects agents' answers to the "
    "broker.reconcile probe before resolving the deposed leader's "
    "in-flight queries (re-attach vs partial/broker_failover).",
)
define_flag(
    "broker_reattach_timeout_s", 15.0,
    "Forwarder wait budget for a re-attached failover query on the new "
    "leader; the inactivity watchdog inside the wait bounds a truly "
    "dead query well before this.",
)
define_flag(
    "client_request_retries", 3,
    "api.Client retries of IDEMPOTENT control-plane requests (agents, "
    "schemas, debug_queries, ...) on BusTimeout. execute_script is "
    "never blind-retried (non-idempotent).",
)
define_flag(
    "client_retry_backoff_ms", 50.0,
    "Initial backoff for api.Client idempotent-request retries; "
    "doubles per attempt (capped at 2s) with +0..25% jitter.",
)

# -- query-lifecycle tracing (exec/trace.py) ---------------------------------
define_flag(
    "trace_ring_size", 128,
    "Finished query traces kept in the engine tracer's ring buffer "
    "(served by /debug/queryz; oldest evicted first).",
)
define_flag(
    "trace_window_sample", 64,
    "Record one per-window stage/compute/stall interval span every N "
    "windows per fragment (1 = every window, 0 = no window spans). "
    "Timestamps only — never forces device sync.",
)
define_flag(
    "trace_export_url", "",
    "OTLP/HTTP base URL (e.g. http://collector:4318) to push finished "
    "query traces to via exec.otel.OTLPHttpExporter; empty keeps traces "
    "in-memory only (ring buffer).",
)
define_flag(
    "slow_query_threshold_ms", 0.0,
    "Queries slower than this (wall-clock ms) dump their full trace to "
    "the 'pixie_tpu.slow_query' logger; 0 disables the slow-query log.",
)

# -- resource bounds + admission control (analysis/bounds.py) ----------------
define_flag(
    "bounds_safety", 2.0,
    "Multiplier on pxbound's predicted resource totals (staged bytes, "
    "rows). Covers run-time effects the plan-time walk cannot see "
    "exactly: overflow-rebucket re-folds, concurrent ingest between "
    "compile and execution, join driver re-staging. The soundness gate "
    "(analysis/bound_check.py) asserts observed <= predicted UNDER "
    "this factor.",
)
define_flag(
    "bounds_presize", True,
    "Grow AggOp.max_groups at compile time to the sketch-NDV group "
    "bound (pxbound) so first-run aggregates start at the predicted "
    "capacity instead of climbing the overflow-doubling ladder (one "
    "whole-table re-fold per rung). Growth only — results identical.",
)
define_flag(
    "bounds_query_budget_mb", 0.0,
    "Per-query budget on pxbound's predicted staged bytes; a plan "
    "predicted over budget fails AT COMPILE with a structured "
    "resource-bound Diagnostic instead of OOMing mid-query. 0 "
    "disables. Sketch-less (unbounded) predictions are never rejected.",
)
define_flag(
    "bounds_device_budget_mb", 0.0,
    "Per-node budget on pxbound's predicted device allocation (staged "
    "window planes, aggregate group state, join build+output buffers); "
    "enforced at compile like bounds_query_budget_mb. 0 disables.",
)
define_flag(
    "admission_bytes_budget_mb", 0.0,
    "Broker admission control: budget on the SUM of in-flight queries' "
    "predicted staged bytes (pxbound predicted_cost). A single query "
    "predicted over the whole budget is rejected with its diagnostic; "
    "a query that merely doesn't fit NOW queues up to "
    "admission_queue_s. 0 disables (every query admitted). Queries "
    "with unknown (sketch-less) predictions are admitted and accounted "
    "at zero.",
)
define_flag(
    "broker_execute_threads", 16,
    "PER-TENANT worker-thread cap for the served broker.execute topic "
    "(serve()). Each in-flight remote request holds one daemon worker "
    "for its whole execution (including admission queueing); a "
    "tenant's requests past its cap wait in that tenant's own FIFO "
    "backlog, so one tenant's parked requests can never starve "
    "another tenant's at the front door, and total threads stay "
    "bounded by cap x the registered tenant set even with admission "
    "control disabled.",
)
define_flag(
    "admission_queue_s", 5.0,
    "How long an admission-controlled query may wait for in-flight "
    "predicted bytes to drain before it is rejected (queue timeout). "
    "0 rejects immediately when the budget is full.",
)
define_flag(
    "admission_tenant_weights", "",
    "Registered tenant set with fair-share weights for broker "
    "admission control, as comma-separated name:weight entries "
    "(e.g. 'dash:4,batch:1'). Each tenant's slice of "
    "admission_bytes_budget_mb is budget * weight / sum(weights); the "
    "default tenant 'shared' is always registered (weight 1 unless "
    "listed) and absorbs queries with no/unknown tenant. Empty = "
    "single shared tenant (the whole budget, pre-tenancy behavior). "
    "Tenant names label metrics, so they MUST come from this set — "
    "services/tenancy.py resolve_tenant() folds anything else into "
    "'shared' (bounded label cardinality).",
)
define_flag(
    "admission_priority_holddown_ms", 0.0,
    "Non-work-conserving grace window for strict-priority admission: "
    "after a priority-p query releases, strictly-lower-priority "
    "waiters stay queued for this many milliseconds. An admitted "
    "query's compute cannot be preempted (queries now overlap on an "
    "engine — pxlock, docs/ANALYSIS.md — but still contend for the "
    "same cores/devices), so without the hold-down a back-to-back "
    "high-priority stream is interleaved with unpreemptible "
    "low-priority work admitted in its ~ms inter-arrival gaps — "
    "head-of-line blocking that moves the high class's p99 however "
    "fair the byte shares are. 0 (default) disables: admission is "
    "work-conserving and purely share/priority ordered.",
)

# -- concurrency verification (analysis/lockdep.py) --------------------------
define_flag(
    "lockdep", False,
    "Runtime lock-order validation (Linux-lockdep style): wraps "
    "threading.Lock/RLock/Condition creation, maintains per-thread "
    "held-stacks and a process-wide observed acquisition-order graph, "
    "and raises (with both stack pairs) at the first acquisition that "
    "would close a cycle. Test/deploy instrumentation — off by "
    "default, zero overhead when off (the raw C lock types are "
    "untouched). run_tests.sh --locks runs the concurrency suites "
    "under it; deploy roles honor it at process start.",
)

# -- device-tier observability (exec/programs.py) ----------------------------
define_flag(
    "program_registry_size",
    512,
    "Compiled-program registry capacity (exec/programs.py): tracked "
    "(program, shape-signature) records — each holding its XLA "
    "executable, compile wall-time and cost/memory analysis — kept in "
    "an LRU; oldest evicted (and recompiled on next use). 0 disables "
    "tracking entirely (jit entry points run unwrapped).",
)
define_flag(
    "device_memory_poll_s",
    0.0,
    "Background device.memory_stats() poll period for per-query peak "
    "device-memory attribution (QueryResourceUsage.device_peak_bytes). "
    "0 disables the poll thread; peaks then come from the query-"
    "boundary samples alone. Gauges refresh at every /metrics scrape "
    "regardless.",
)
define_flag(
    "admission_observed_floor",
    True,
    "Broker admission control floors predicted_cost at the observed "
    "per-script-hash bytes_staged history from finished query traces "
    "(the __queries__ feedback loop): a sketch-less UNKNOWN prediction "
    "with history is admitted against the observed bytes instead of "
    "zero, and a known prediction below observed reality is raised to "
    "it. Only matters while admission_bytes_budget_mb > 0.",
)

# -- result cache + materialized views (exec/result_cache.py, exec/views.py) -
define_flag(
    "result_cache_mb", 0,
    "Byte budget (MB) for the watermark-validated merged-result cache "
    "(broker execute_script + local engine.execute_query). A repeat of "
    "a script whose scanned tables' cluster watermarks have not "
    "advanced past the per-script staleness budget is served from the "
    "cache with zero compile/admission/dispatch cost. 0 disables "
    "(every query executes; the pre-cache behavior). Validity is "
    "purely watermark comparison — never wall-clock TTL.",
)
define_flag(
    "result_cache_staleness_ms", 0.0,
    "Default per-script staleness budget (ms) for result-cache hits "
    "when the script manifest carries no staleness_budget_ms field: a "
    "cached result whose stored watermarks trail the current ones by "
    "at most this much still serves (freshness_lag_ms re-stamped "
    "against the CURRENT watermark). 0 = exact-watermark hits only.",
)
define_flag(
    "view_auto_min_runs", 0,
    "Observed-frequency heuristic for incremental materialized views: "
    "a script executed at least this many times (ObservedCostIndex "
    "runs + live counts) is auto-registered as a continuously "
    "maintained view, answered as finalize-over-state instead of a "
    "full rescan. 0 disables auto-registration (manifest "
    "'materialize: true' opt-in still registers).",
)
define_flag(
    "pushdown_union_agg", True,
    "Distributed planner: place PEM-safe UnionOps (all inputs "
    "PEM-resident and non-blocking, sole consumer chain ending at a "
    "full AggOp) on the data agents so the downstream aggregate splits "
    "into partial-on-PEM + AGG_STATE_MERGE, shipping sketch-sized "
    "merge state (HLL registers, t-digest centroids) instead of "
    "pre-agg rows over the union's ROW_GATHER bridges.",
)

# -- self-observability (services/telemetry.py) ------------------------------
define_flag(
    "self_telemetry", True,
    "Agents fold their engine's finished query traces + resource "
    "records into the __queries__/__spans__/__agents__ tables "
    "(PxL-queryable through the normal engine path) and publish "
    "distributed-trace span summaries for the broker's /debug/tracez.",
)
define_flag(
    "telemetry_table_mb", 8,
    "Per-table byte budget (MB) for the self-telemetry tables; each "
    "table's ring expires its own oldest rows at the budget.",
)
define_flag(
    "self_profiling", True,
    "Deploy roles run the self-sampling perf profiler "
    "(ingest/profiler.py): agents AND the broker fold their own "
    "Python stacks — attributed with {qid, script_hash, tenant, "
    "phase} from the thread attribution registry — into the "
    "__stacks__ telemetry ring (px/query_cpu / px/tenant_cpu) plus "
    "the anonymous stack_traces.beta aggregate "
    "(px/perf_flamegraph), and serve merged flames via "
    "/debug/pprof + /debug/flamez. Off = no sampling thread work "
    "at all.",
)
define_flag(
    "bus_telemetry", True,
    "Buses (MessageBus/RemoteBus) stamp the transport tier "
    "(services/busstats.py): per-topic-class publish/deliver/byte "
    "counters, dispatcher-lag + handler service-time histograms, "
    "queue-depth high-water gauges, wire frame/byte/RTT accounting — "
    "folded into the __bus__ telemetry ring on the heartbeat cadence "
    "and served at /debug/busz. Off = buses carry no stats object "
    "(the A/B overhead baseline).",
)
define_flag(
    "slow_handler_threshold_ms", 0.0,
    "Bus handlers slower than this (service time, ms) log topic, "
    "class, service/lag times to the 'pixie_tpu.slow_handler' logger "
    "and count in pixie_bus_slow_handlers_total; 0 disables the "
    "slow-handler log. The transport-tier twin of "
    "slow_query_threshold_ms.",
)
define_flag(
    "profile_summary_stacks", 512,
    "Per-profiler cap on distinct (stack, attribution) keys kept in "
    "the cumulative folded-stack summary that heartbeats ship for "
    "cluster merge; over the cap the coldest stacks age out "
    "(hottest-kept eviction, counts stay monotonic for survivors).",
)
