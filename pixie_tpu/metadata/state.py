"""Agent metadata state: k8s entities + UPID -> entity mapping.

Reference parity: ``src/shared/metadata/metadata_state.h`` —
``K8sMetadataState`` (:47; pods/services/namespaces by UID and IP) and
``AgentMetadataState`` (:251; UPID -> PIDInfo :290). The reference builds
this from NATS ``ResourceUpdate`` streams + /proc scans
(``state_manager.h:115``); here updates arrive via the ``apply_update``
dict API (the receiving surface a control plane feeds).

UPID is the 128-bit {asid(u32), pid(u32), start_ticks(u64)} join key
between traces and k8s metadata (``src/shared/upid``); device-side it is
an (hi, lo) uint64 pair.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class UPID:
    asid: int
    pid: int
    start_ticks: int

    @property
    def hi(self) -> int:
        return ((self.asid & 0xFFFFFFFF) << 32) | (self.pid & 0xFFFFFFFF)

    @property
    def lo(self) -> int:
        return self.start_ticks & 0xFFFFFFFFFFFFFFFF

    @property
    def value(self) -> int:
        return (self.hi << 64) | self.lo

    def __str__(self) -> str:
        return f"{self.asid}:{self.pid}:{self.start_ticks}"

    @classmethod
    def parse(cls, s: str) -> "UPID":
        asid, pid, ticks = s.split(":")
        return cls(int(asid), int(pid), int(ticks))


@dataclass
class PodInfo:
    uid: str
    name: str
    namespace: str
    node_name: str = ""
    phase: str = "RUNNING"
    ip: str = ""
    service_uids: tuple = ()
    start_time_ns: int = 0
    stop_time_ns: int = 0  # 0 = still alive

    @property
    def qualified_name(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class ServiceInfo:
    uid: str
    name: str
    namespace: str
    cluster_ip: str = ""

    @property
    def qualified_name(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class ContainerInfo:
    cid: str
    name: str
    pod_uid: str


@dataclass
class _PIDInfo:
    upid: UPID
    pod_uid: str
    container_id: str = ""
    cmdline: str = ""


@dataclass
class MetadataState:
    """Mutable metadata snapshot store (thread-safe via a coarse lock).

    ``epoch`` increments on every mutation so bound query closures can be
    invalidated (queries snapshot the state at compile/bind time — the
    reference similarly hands each query an AgentMetadataState snapshot).
    """

    asid: int = 0
    pods: dict = field(default_factory=dict)  # uid -> PodInfo
    services: dict = field(default_factory=dict)  # uid -> ServiceInfo
    containers: dict = field(default_factory=dict)  # cid -> ContainerInfo
    namespaces: set = field(default_factory=set)
    pids: dict = field(default_factory=dict)  # (hi, lo) -> _PIDInfo
    ip_to_pod: dict = field(default_factory=dict)  # ip -> pod uid
    epoch: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    # -- update application (the ResourceUpdate-handler surface) -------------
    def add_pod(self, uid, name, namespace, node_name="", ip="",
                service_uids=(), phase="RUNNING", start_time_ns=0):
        with self._lock:
            self.pods[uid] = PodInfo(
                uid=uid, name=name, namespace=namespace, node_name=node_name,
                ip=ip, service_uids=tuple(service_uids), phase=phase,
                start_time_ns=start_time_ns,
            )
            self.namespaces.add(namespace)
            if ip:
                self.ip_to_pod[ip] = uid
            self.epoch += 1

    def add_service(self, uid, name, namespace, cluster_ip=""):
        with self._lock:
            self.services[uid] = ServiceInfo(uid, name, namespace, cluster_ip)
            self.namespaces.add(namespace)
            self.epoch += 1

    def add_container(self, cid, name, pod_uid):
        with self._lock:
            self.containers[cid] = ContainerInfo(cid, name, pod_uid)
            self.epoch += 1

    def add_process(self, upid: UPID, pod_uid: str, container_id: str = "",
                    cmdline: str = ""):
        with self._lock:
            self.pids[(upid.hi, upid.lo)] = _PIDInfo(
                upid, pod_uid, container_id, cmdline
            )
            self.epoch += 1

    def remove_pod(self, uid, stop_time_ns: int = 1):
        with self._lock:
            if uid in self.pods:
                self.pods[uid].stop_time_ns = stop_time_ns
            self.epoch += 1

    def apply_update(self, update: dict):
        """Apply one ResourceUpdate-shaped dict (the NATS message analog):
        {"kind": "pod"|"service"|"container"|"process", ...fields}."""
        kind = update.get("kind")
        u = {k: v for k, v in update.items() if k != "kind"}
        if kind == "pod":
            self.add_pod(**u)
        elif kind == "service":
            self.add_service(**u)
        elif kind == "container":
            self.add_container(**u)
        elif kind == "process":
            upid = u.pop("upid")
            if isinstance(upid, str):
                upid = UPID.parse(upid)
            self.add_process(upid, **u)
        else:
            raise ValueError(f"unknown metadata update kind {kind!r}")

    # -- query-side accessors ------------------------------------------------
    def pod_of_upid(self, hi: int, lo: int) -> Optional[PodInfo]:
        p = self.pids.get((hi, lo))
        return self.pods.get(p.pod_uid) if p else None

    def service_of_pod(self, pod: PodInfo) -> Optional[ServiceInfo]:
        for suid in pod.service_uids:
            svc = self.services.get(suid)
            if svc:
                return svc
        return None

    def snapshot_entries(self):
        """(upid_his, upid_los, per-attribute string lists) for UDF binding."""
        with self._lock:
            entries = list(self.pids.values())
            out = {
                "hi": [p.upid.hi for p in entries],
                "lo": [p.upid.lo for p in entries],
                "pod_id": [], "pod_name": [], "namespace": [], "node_name": [],
                "service_id": [], "service_name": [], "container_id": [],
                "container_name": [], "cmdline": [],
            }
            for p in entries:
                pod = self.pods.get(p.pod_uid)
                svc = self.service_of_pod(pod) if pod else None
                cont = self.containers.get(p.container_id)
                out["pod_id"].append(pod.uid if pod else "")
                out["pod_name"].append(pod.qualified_name if pod else "")
                out["namespace"].append(pod.namespace if pod else "")
                out["node_name"].append(pod.node_name if pod else "")
                out["service_id"].append(svc.uid if svc else "")
                out["service_name"].append(svc.qualified_name if svc else "")
                out["container_id"].append(p.container_id)
                out["container_name"].append(cont.name if cont else "")
                out["cmdline"].append(p.cmdline)
            return out
