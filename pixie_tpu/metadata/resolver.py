"""``df.ctx['service']`` resolution: metadata property -> UDF call.

Reference parity: ``src/carnot/planner/metadata/metadata_handler.h:72`` +
the convert_metadata_rule analyzer pass — a ctx property becomes an
``upid_to_*`` function call on the table's UPID column.
"""

from __future__ import annotations

from ..exec.plan import ColumnRef, FuncCall
from ..planner.objects import ColumnExpr, PxLError

# ctx key -> upid_to_* UDF
_CTX_FUNCS = {
    "pod_id": "upid_to_pod_id",
    "pod": "upid_to_pod_name",
    "pod_name": "upid_to_pod_name",
    "namespace": "upid_to_namespace",
    "node": "upid_to_node_name",
    "node_name": "upid_to_node_name",
    "service_id": "upid_to_service_id",
    "service": "upid_to_service_name",
    "service_name": "upid_to_service_name",
    "container_id": "upid_to_container_id",
    "container": "upid_to_container_name",
    "container_name": "upid_to_container_name",
    "cmdline": "upid_to_cmdline",
    "cmd": "upid_to_cmdline",
}

_UPID_COLUMNS = ("upid", "upid_")


def resolve_ctx(df, key: str) -> ColumnExpr:
    if key not in _CTX_FUNCS:
        raise PxLError(
            f"unknown metadata property ctx[{key!r}]; available: "
            f"{sorted(set(_CTX_FUNCS))}"
        )
    upid_col = next(
        (c for c in _UPID_COLUMNS if df.relation.has_column(c)), None
    )
    if upid_col is None:
        raise PxLError(
            f"ctx[{key!r}] requires a 'upid' column in the table "
            f"(have: {list(df.relation.column_names)})"
        )
    fname = _CTX_FUNCS[key]
    if not df.builder.registry.has_scalar(fname):
        raise PxLError(
            f"ctx[{key!r}]: metadata functions are not registered on this "
            "engine (no metadata state attached)"
        )
    return ColumnExpr(FuncCall(fname, (ColumnRef(upid_col),)), df)
