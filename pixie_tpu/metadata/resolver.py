"""``df.ctx['service']`` resolution: metadata property -> UDF call.

Reference parity: ``src/carnot/planner/metadata/metadata_handler.h:72`` +
the convert_metadata_rule analyzer pass — a ctx property becomes an
``upid_to_*`` function call on the table's UPID column. The mapping is
driven by UDF *semantic-type annotations* (``udf/type_inference.h``
analog): any registered UPID->STRING function whose return semantic
type names the property answers it, so engines that register custom
metadata functions get ctx resolution without touching this module.
"""

from __future__ import annotations

from ..exec.plan import ColumnRef, FuncCall
from ..planner.objects import ColumnExpr, PxLError
from ..types.dtypes import DataType
from ..types.semantic import CTX_KEYS, SemanticType

# id-valued ctx keys have no semantic type (they are opaque uids); they
# resolve by conventional function name.
_ID_CTX_FUNCS = {
    "pod_id": "upid_to_pod_id",
    "service_id": "upid_to_service_id",
    "container_id": "upid_to_container_id",
    "cmdline": "upid_to_cmdline",
    "cmd": "upid_to_cmdline",
}

_UPID_COLUMNS = ("upid", "upid_")


def _semantic_ctx_funcs(registry) -> dict[str, str]:
    """ctx key -> function name, derived from semantic annotations: a
    scalar UDF taking (UINT128) and returning a string with e.g.
    ST_SERVICE_NAME answers ctx['service'] / ctx['service_name'].

    The map depends only on the registry's contents; cache it on the
    registry object (registries are cloned, not mutated, when metadata
    rebinds — see Engine.set_metadata_state)."""
    cached = getattr(registry, "_ctx_funcs_cache", None)
    if cached is not None:
        return cached
    out: dict[str, str] = {}
    for fname in registry.scalar_names():
        for ov in registry.scalar_overloads(fname):
            if ov.arg_types != (DataType.UINT128,):
                continue
            try:
                st = SemanticType(ov.semantic_type)
            except ValueError:
                continue  # user-defined semantic value: no ctx mapping
            keys = CTX_KEYS.get(st)
            if not keys:
                continue
            for k in keys:
                out.setdefault(k, fname)
    registry._ctx_funcs_cache = out
    return out


def available_ctx_keys(registry) -> list[str]:
    return sorted(set(_ID_CTX_FUNCS) | set(_semantic_ctx_funcs(registry)))


def resolve_ctx(df, key: str) -> ColumnExpr:
    registry = df.builder.registry
    funcs = _semantic_ctx_funcs(registry)
    fname = funcs.get(key) or _ID_CTX_FUNCS.get(key)
    if fname is None:
        known = available_ctx_keys(registry)
        if key in (
            "pod", "pod_name", "service", "service_name", "namespace",
            "node", "node_name", "container", "container_name",
        ):
            raise PxLError(
                f"ctx[{key!r}]: metadata functions are not registered on "
                "this engine (no metadata state attached)"
            )
        raise PxLError(
            f"unknown metadata property ctx[{key!r}]; available: {known}"
        )
    upid_col = next(
        (c for c in _UPID_COLUMNS if df.relation.has_column(c)), None
    )
    if upid_col is None:
        raise PxLError(
            f"ctx[{key!r}] requires a 'upid' column in the table "
            f"(have: {list(df.relation.column_names)})"
        )
    if not registry.has_scalar(fname):
        raise PxLError(
            f"ctx[{key!r}]: metadata functions are not registered on this "
            "engine (no metadata state attached)"
        )
    return ColumnExpr(FuncCall(fname, (ColumnRef(upid_col),)), df)
