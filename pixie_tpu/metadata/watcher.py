"""Metadata watcher: streams resource updates into MetadataState.

Reference parity: the metadata service's k8s watcher
(``/root/reference/src/vizier/services/metadata/controllers/k8smeta/
k8s_metadata_handler.go`` — watch pods/services/endpoints, convert to
ResourceUpdates with monotonically increasing resource versions, replay
missed ranges on reconnect). Without a k8s API in scope, the watcher
consumes the same ResourceUpdate-shaped dicts from any iterable feed —
an in-memory queue, a JSONL file tail, or a bus topic — tracks the
resource version high-water mark, and applies updates to a
``MetadataState`` under a lock, optionally fanning out to subscribers
(the NATS ``MetadataUpdates`` publication analog).
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Optional

from .state import MetadataState


class MetadataWatcher:
    """Applies versioned ResourceUpdates to a MetadataState."""

    def __init__(self, state: Optional[MetadataState] = None):
        self.state = state if state is not None else MetadataState()
        self.resource_version = 0
        self.updates_applied = 0
        self.updates_skipped = 0  # stale (<= high-water) versions
        self._lock = threading.Lock()
        self._subscribers: list[Callable] = []

    def subscribe(self, fn: Callable) -> None:
        """fn(update_dict) after each applied update (MetadataUpdates
        publication)."""
        self._subscribers.append(fn)

    def apply(self, update: dict) -> bool:
        """Apply one update; returns False for stale resource versions.

        Updates carry an optional monotonically-increasing ``rv``; absent
        rv means unversioned (always applied) — the reference's full-sync
        path. Out-of-order versioned updates are skipped, which is what
        makes reconnect replays idempotent.
        """
        rv = update.get("rv")
        with self._lock:
            if rv is not None:
                if rv <= self.resource_version:
                    self.updates_skipped += 1
                    return False
                self.resource_version = rv
            payload = {k: v for k, v in update.items() if k != "rv"}
            self.state.apply_update(payload)
            self.updates_applied += 1
        for fn in self._subscribers:
            fn(update)
        return True

    def apply_all(self, feed) -> int:
        """Drain an iterable of update dicts; returns applied count."""
        n = 0
        for u in feed:
            if self.apply(u):
                n += 1
        return n

    def load_jsonl(self, path: str) -> int:
        """Replay a recorded update log (one JSON object per line) — the
        missed-range replay path on restart."""
        with open(path) as f:
            return self.apply_all(
                json.loads(line) for line in f if line.strip()
            )

    def missing_range(self, from_rv: int, to_rv: int) -> tuple[int, int]:
        """(from, to) of updates a reconnecting consumer must replay
        (GetUpdatesForRange analog)."""
        with self._lock:
            return (min(from_rv, self.resource_version),
                    min(to_rv, self.resource_version))
