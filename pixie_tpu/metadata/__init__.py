"""K8s metadata subsystem: entity state + UPID resolution UDFs.

Reference parity: ``src/shared/metadata/`` (K8sMetadataState
``metadata_state.h:47``, AgentMetadataState ``:251`` mapping UPID ->
PIDInfo -> pod/service) and the metadata UDFs in
``src/carnot/funcs/metadata/``.
"""

from .state import ContainerInfo, MetadataState, PodInfo, ServiceInfo, UPID

__all__ = ["ContainerInfo", "MetadataState", "PodInfo", "ServiceInfo", "UPID"]
