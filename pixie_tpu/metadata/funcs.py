"""Metadata UDFs: UPID/IP/entity-id -> k8s names, bound to a state snapshot.

Reference parity: ``src/carnot/funcs/metadata/`` — upid_to_pod_name,
upid_to_service_name, pod_id_to_*, ip_to_pod_id, etc.

TPU-first design: the UPID family is a DEVICE lookup — the host builds a
bounded-probe hash table (``pixie_tpu.ops.hashtable``) from the metadata
snapshot, and the compiled fragment resolves UPIDs with a fixed number of
gathers, emitting ids into an entity-name dictionary (no per-row host
callbacks, unlike the reference's per-row C++ UDF calls). The id-string
family (pod_id_to_pod_name, ip_to_pod_id, ...) runs HOST_DICT: once per
distinct string, O(dictionary) not O(rows).
"""

from __future__ import annotations

import numpy as np

from ..ops.hashtable import build_table, device_lookup
from ..types.strings import StringDictionary
from ..udf.udf import Executor, STRING, UINT128
from .state import MetadataState

from ..types.semantic import SemanticType as ST

# upid_to_* attribute -> (snapshot_entries key, semantic type of result)
_UPID_ATTRS = {
    "upid_to_pod_id": ("pod_id", ST.ST_NONE),
    "upid_to_pod_name": ("pod_name", ST.ST_POD_NAME),
    "upid_to_namespace": ("namespace", ST.ST_NAMESPACE_NAME),
    "upid_to_node_name": ("node_name", ST.ST_NODE_NAME),
    "upid_to_service_id": ("service_id", ST.ST_NONE),
    "upid_to_service_name": ("service_name", ST.ST_SERVICE_NAME),
    "upid_to_container_id": ("container_id", ST.ST_NONE),
    "upid_to_container_name": ("container_name", ST.ST_CONTAINER_NAME),
    "upid_to_cmdline": ("cmdline", ST.ST_NONE),
}


_HOST_FUNC_NAMES = (
    "pod_id_to_pod_name", "pod_id_to_namespace", "pod_id_to_node_name",
    "pod_id_to_service_name", "pod_id_to_service_id",
    "service_id_to_service_name", "ip_to_pod_id", "pod_name_to_pod_id",
    "service_name_to_service_id",
)
METADATA_FUNC_NAMES = tuple(_UPID_ATTRS) + _HOST_FUNC_NAMES


def register_metadata_funcs(reg, state: MetadataState) -> None:
    """Register metadata UDFs bound to a snapshot of ``state``.

    Call again (on a fresh Registry) after metadata changes; the engine
    re-binds per query the way the reference hands each query a fresh
    AgentMetadataState snapshot.
    """
    import jax.numpy as jnp

    snap = state.snapshot_entries()
    n = len(snap["hi"])
    his = np.asarray(snap["hi"], dtype=np.uint64)
    los = np.asarray(snap["lo"], dtype=np.uint64)
    table = build_table((his, los), np.arange(n, dtype=np.int32))
    # Constants stay numpy until TRACE time: eagerly-created jax Arrays
    # captured as jit constants poison axon-tunnel dispatch. device_lookup
    # converts the table planes inline during tracing.

    for fname, (attr, st) in _UPID_ATTRS.items():
        d = StringDictionary()
        ids = np.asarray(d.encode(snap[attr] + [""]))  # [n+1]; n = miss -> ""

        def fn(upid, _tbl=table, _ids=ids, _n=n):
            hi, lo = upid
            vals, found = device_lookup(_tbl, (hi, lo))
            return jnp.asarray(_ids)[jnp.where(found, vals, _n)]

        reg.scalar(
            fname, (UINT128,), STRING, fn, out_dict=d,
            doc=f"Resolve a UPID to its {attr.replace('_', ' ')} "
                "(empty string when unknown).",
            semantic_type=int(st),
        )

    # -- id/ip string translations (HOST_DICT: once per distinct value) ------
    pods, services = dict(state.pods), dict(state.services)
    ip_to_pod = dict(state.ip_to_pod)

    def _pod(pid):
        return pods.get(pid)

    host = dict(executor=Executor.HOST_DICT, dict_arg=0)
    reg.scalar("pod_id_to_pod_name", (STRING,), STRING,
               lambda s: p.qualified_name if (p := _pod(s)) else "", **host,
               doc="Pod UID to namespace/name.")
    reg.scalar("pod_id_to_namespace", (STRING,), STRING,
               lambda s: p.namespace if (p := _pod(s)) else "", **host)
    reg.scalar("pod_id_to_node_name", (STRING,), STRING,
               lambda s: p.node_name if (p := _pod(s)) else "", **host)
    reg.scalar(
        "pod_id_to_service_name", (STRING,), STRING,
        lambda s: (
            svc.qualified_name
            if (p := _pod(s)) and (svc := state.service_of_pod(p))
            else ""
        ),
        **host, doc="Pod UID to owning service namespace/name.",
    )
    reg.scalar(
        "pod_id_to_service_id", (STRING,), STRING,
        lambda s: (
            svc.uid
            if (p := _pod(s)) and (svc := state.service_of_pod(p))
            else ""
        ),
        **host,
    )
    reg.scalar("service_id_to_service_name", (STRING,), STRING,
               lambda s: v.qualified_name if (v := services.get(s)) else "",
               **host)
    reg.scalar("ip_to_pod_id", (STRING,), STRING,
               lambda s: ip_to_pod.get(s, ""), **host,
               doc="Cluster pod IP to pod UID (empty for external IPs).")
    reg.scalar(
        "pod_name_to_pod_id", (STRING,), STRING,
        lambda s: next(
            (p.uid for p in pods.values() if p.qualified_name == s), ""
        ),
        **host,
    )
    reg.scalar(
        "service_name_to_service_id", (STRING,), STRING,
        lambda s: next(
            (v.uid for v in services.values() if v.qualified_name == s), ""
        ),
        **host,
    )
